"""The epoch-loop orchestrator — the framework's user-facing core.

Capability twin of the reference ``trainer/trainer.py`` abstract ``Trainer``:
the same template-method surface (the nine user hooks, ``trainer/trainer.py:
219-253``), the same constructor contract (``:15-24``), the same epoch loop —
resume-aware range (``:110``), periodic validation with best-model tracking
(``:114-135``), per-epoch train loop with progress bar and loss collection
(``:138-156``), scheduler reporting (``:159-160``), last/periodic
checkpointing (``:163-172``), mean-loss logging (``:175-178``) — rebuilt on a
functional core:

* mutable ``self.model/optimizer/scheduler`` become one :class:`TrainState`
  pytree threaded through a jitted step (``train.engine.TrainEngine``);
* DDP + NCCL barriers disappear: the batch is sharded over the mesh's ``data``
  axis, XLA inserts and overlaps the gradient all-reduce, and checkpoint saves
  are collective (Orbax), so there is no rank-0 barrier choreography;
* validation is *collective* (every device evaluates a shard) instead of the
  reference's rank-0-only full-dataset pass (``:184-206``, SURVEY.md §2e), and
  reported metrics are global means, not per-rank locals;
* the scheduler is an optax per-step schedule fused into the optimizer, so
  "scheduler state" is just ``state.step``.

Hook mapping (reference -> here):

=================  ==========================================================
``build_train_dataset``  same name; returns an indexable source (may carry a
                         ``.transform`` applied by the loader)
``build_val_dataset``    same (fixed to default to *val* data, §2e bug)
``build_model``          same; returns a Flax module
``build_criterion``      same; returns ``(outputs, batch) -> (loss, metrics)``
``build_optimizer``      same; receives the schedule, returns an optax
                         ``GradientTransformation``
``build_scheduler``      same; returns an optax per-step ``Schedule`` or a
                         constant lr
``preprocess_batch``     same; host-side, before device transfer (the H2D copy
                         itself is the framework's job now)
``train_step``           same name; ``(state, batch) -> (state, metrics)`` —
                         default delegates to the compiled engine step
``validate_step``        same name; ``(state, batch) -> metrics`` — default is
                         the compiled collective eval step
=================  ==========================================================
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_training_pytorch_tpu.checkpoint import (
    BEST,
    LAST,
    CheckpointError,
    CheckpointManager,
    epoch_checkpoint_name,
)
from distributed_training_pytorch_tpu.data import (
    ShardedLoader,
    device_prefetch,
    device_prefetch_chained,
)
from distributed_training_pytorch_tpu.fault.watchdog import StepWatchdog
from distributed_training_pytorch_tpu.memory import (
    resolve_preflight,
    run_preflight,
    window_memory_fields,
)
from distributed_training_pytorch_tpu.parallel import elastic as elastic_lib
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.precision import (
    get_policy,
    is_dynamic,
    resolve_loss_scale,
)
from distributed_training_pytorch_tpu.profiling import (
    StepTraceCapture,
    resolve_profile,
)
from distributed_training_pytorch_tpu.resilience import AsyncCheckpointSaver
from distributed_training_pytorch_tpu.telemetry import (
    EventLog,
    GoodputMeter,
    resolve_telemetry,
)
from distributed_training_pytorch_tpu.telemetry.events import claim_attempt
from distributed_training_pytorch_tpu.telemetry import doctor as telemetry_doctor
from distributed_training_pytorch_tpu.telemetry import mfu as telemetry_mfu
from distributed_training_pytorch_tpu.telemetry import straggler as straggler_lib
from distributed_training_pytorch_tpu.train import (
    NonFiniteLossError,
    TrainEngine,
    make_supervised_loss,
)
from distributed_training_pytorch_tpu.utils.tensorboard import MetricsWriter


class Trainer:
    """Subclass, implement the hooks, call :meth:`train`.

    Constructor args mirror ``trainer/trainer.py:15-24``; ``pin_memory`` is
    accepted for source compatibility but ignored (device transfer is async
    via the prefetcher — there is no pageable/pinned distinction to manage).
    """

    def __init__(
        self,
        max_epoch: int,
        batch_size: int,
        pin_memory: bool = False,
        have_validate: bool = False,
        save_best_for: tuple[str, str] | None = None,
        save_period: int | None = None,
        save_folder: str = ".",
        snapshot_path: str | None = None,
        logger=None,
        *,
        mesh: jax.sharding.Mesh | None = None,
        sharding_rules="auto",
        fsdp_min_size: int = 2**18,
        seed: int = 0,
        accum_steps: int = 1,
        num_workers: int = 8,
        prefetch_batches: int = 2,
        log_every: int = 50,
        chain_steps: int = 1,
        last_save_period: int = 1,
        async_checkpoint: bool = True,
        profile_dir: str | None = None,
        profile_steps: int = 5,
        progress: bool = True,
        save_on_preemption: bool = True,
        preemption_check_every: int = 20,
        max_checkpoints_to_keep: int | None = None,
        tensorboard_dir: str | None = None,
        nan_policy: str | None = None,
        skip_corrupt_records: bool = False,
        step_timeout: float | None = None,
        fault_plan=None,
        precision=None,
        loss_scale=None,
        telemetry=None,
        profile=None,
        preflight=None,
    ):
        # Logger closure — exact contract of ``trainer/trainer.py:26``.
        self.log = (
            (lambda msg, log_type="info": logger.log(msg, log_type))
            if logger is not None
            else (lambda msg, log_type="info": print(f"{log_type.upper()}: {msg}"))
        )

        self.max_epoch = max_epoch
        self.batch_size = batch_size
        self.have_validate = have_validate
        self.save_best_for = save_best_for
        self.save_period = save_period
        self.seed = seed
        self.accum_steps = accum_steps
        self.num_workers = num_workers
        # Host-side batch look-ahead (ShardedLoader window). Composes with
        # the device-side device_prefetch(depth=2) ring in train_epoch: this
        # bounds host decode-ahead, that bounds on-device staging.
        self.prefetch_batches = prefetch_batches
        self.log_every = log_every
        # The reference saves `last` every epoch (``trainer/trainer.py:163``)
        # — the right default on local disk. When the checkpoint path is slow
        # (multi-GB states, or a chip behind a thin link where the d2h
        # snapshot dominates the epoch), raise this to save `last` every N
        # epochs; preemption saves still fire regardless.
        self.last_save_period = max(1, int(last_save_period))
        self.cur_epoch = 0
        # Tracing knobs. `profile_dir`/`profile_steps` is the legacy surface
        # (SURVEY.md §5; analog of the reference's NCCL flight-recorder
        # buffer, run.sh:8): a raw jax.profiler trace of the first epoch's
        # steady-state steps, forced onto the single-step path. `profile=`
        # (a profiling.ProfileConfig, or a trace-dir string; ISSUE 6,
        # docs/profiling.md) is the first-class capture: it traces a window
        # of the REAL execution (chained windows included), analyzes it into
        # a StepProfile (device-time attribution + dispatch-gap audit), and
        # emits a `profile_capture` telemetry event — while keeping the run
        # bit-exact and trace-count-identical with profile=None
        # (test-enforced). The two knobs are mutually exclusive: both would
        # race one global jax.profiler session.
        self.profile = resolve_profile(profile)
        if self.profile is not None and profile_dir is not None:
            raise ValueError(
                "pass either profile= (ProfileConfig; analyzed capture) or "
                "profile_dir= (legacy raw trace), not both — they would race "
                "the one jax.profiler session"
            )
        if self.profile is not None and self.profile.dir is None:
            self.profile = dataclasses.replace(
                self.profile, dir=os.path.join(save_folder, "profile")
            )
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self._profiled = False
        self.progress = progress
        # Preemption-aware checkpointing (SURVEY.md §5.3's named upgrade over
        # the reference's manual-restart-only recovery): SIGTERM — what cloud
        # schedulers send ahead of eviction, delivered to every host of the
        # job — sets a flag the epoch loop polls; the loop then saves a
        # resumable snapshot and returns cleanly. The handler itself only
        # flips the flag (checkpoint saves are collective and must not run in
        # signal context).
        self._preempted = False
        self._epoch_interrupted = False
        self._prev_sigterm = None
        self._sigterm_installed = False
        self.save_on_preemption = save_on_preemption
        # Multi-host SIGTERM reaction latency bound: every `preemption_check_
        # every` steps all hosts vote (one tiny allgather — the only intra-
        # epoch host sync besides log_every). 0 = epoch boundaries only.
        self.preemption_check_every = preemption_check_every
        # Optional TensorBoard scalars (SURVEY §5.5 upgrade; process 0 only).
        self.metrics_writer = MetricsWriter(tensorboard_dir)

        # Graceful degradation (fault/ subsystem). nan_policy governs steps
        # whose loss/grads go non-finite:
        #   None                 — legacy behavior: train on, no guard;
        #   "raise"              — NonFiniteLossError at the next host sync
        #                          point (log_every / epoch end);
        #   "skip"               — the engine guard drops the update (params
        #                          untouched, step advances), counted in
        #                          self.nonfinite_steps;
        #   "restore_last_good"  — like "skip", plus the state is rolled back
        #                          to the newest VALID checkpoint at the next
        #                          host sync point after a poisoned step.
        if nan_policy not in (None, "raise", "skip", "restore_last_good"):
            raise ValueError(
                f"nan_policy must be None|raise|skip|restore_last_good, got {nan_policy!r}"
            )
        self.nan_policy = nan_policy
        self.nonfinite_steps = 0
        self.nonfinite_rollbacks = 0
        # Mixed precision (precision/ subsystem; docs/mixed_precision.md).
        # `precision` names a dtype policy ("fp32" default — bit-exact with
        # pre-precision behavior, test-enforced; "bf16" = fp32 master params
        # + bf16 compute; "fp16" adds dynamic loss scaling automatically).
        # `loss_scale` overrides the scaling choice ("dynamic" | "none" | a
        # precision.DynamicScale/NoOpScale instance; None = policy default).
        # Resolved BEFORE the build hooks so build_model can read
        # self.model_dtype and match its activation dtype to the policy.
        # precision_requested distinguishes an explicit precision="fp32" from
        # an unset knob (the resolved Policy is identical) — entries with a
        # legacy non-fp32 model default honor the explicit request.
        self.precision_requested = precision is not None
        self.precision = get_policy(precision)
        self._initial_loss_scale = resolve_loss_scale(loss_scale, self.precision)
        if self.precision.compute_dtype == jnp.float16 and not is_dynamic(
            self._initial_loss_scale
        ):
            raise ValueError(
                "precision='fp16' requires dynamic loss scaling (fp16 grads "
                "underflow below ~6e-5 without it): leave loss_scale unset "
                "or pass loss_scale='dynamic'. Use precision='bf16' for "
                "scale-free low precision — bf16 keeps fp32's exponent range."
            )
        if is_dynamic(self._initial_loss_scale) and nan_policy in (
            "raise",
            "restore_last_good",
        ):
            raise ValueError(
                f"nan_policy={nan_policy!r} is incompatible with dynamic loss "
                "scaling: overflow-skip + backoff IS the scale calibration "
                "mechanism — 'raise' would abort normal fp16 training on the "
                "first benign overflow, and 'restore_last_good' would roll "
                "the whole state back to an old checkpoint (undoing the "
                "backoff, so the overflow repeats) every time the scale "
                "probes too high. Use nan_policy=None or 'skip' (skipped "
                "steps are still counted once in nonfinite_steps and "
                "state.loss_scale.skipped_steps)."
            )
        self.skip_corrupt_records = skip_corrupt_records
        # Wall-clock hung-step watchdog: past `step_timeout` seconds without
        # a completed step, SIGTERM ourselves — the preemption handler then
        # turns the hang into a resumable save at the next safe point.
        self.step_timeout = step_timeout
        # The timeout actually armed (step_timeout x chain_steps under
        # chaining — set by train_epoch, reported by _on_hung_step).
        self._watchdog_timeout = step_timeout
        # Deterministic fault injection (tests; None in production).
        self.fault_plan = fault_plan
        # On-device chained execution (perf): windows of `chain_steps` train
        # steps dispatch as ONE compiled program (engine.train_steps_chained),
        # eliminating per-step host dispatch from the hot loop — the regime
        # the bench's chained mode measures, now in real training. Per-step
        # metrics come back as scan outputs so loss logging and nonfinite
        # accounting stay exact; the epoch tail, the resume-realignment
        # prefix, the profiled first-epoch prefix, and any window with
        # pending fault injections automatically fall back to single-step
        # execution (bit-exact either way — test-enforced).
        self.chain_steps = int(chain_steps)
        self._validate_chain_config()
        # Mid-epoch resume position (set when restoring a preemption save's
        # loop state; consumed by the first trained epoch).
        self._resume_step_in_epoch = 0
        self._interrupted_at_step = 0

        # Save folder layout: <save_folder>/weights/<name> (``:29-32``).
        # Asynchrony lives in the resilience layer now (ISSUE 5), not in the
        # manager: the manager commits synchronously (each save it runs is
        # fully durable when the call returns), and `async_checkpoint=True`
        # routes periodic/best saves through AsyncCheckpointSaver — a fast
        # device->host snapshot on this thread, the staging+manifest+rename
        # commit on a background thread. Preemption/watchdog saves always
        # commit synchronously (emergency path) regardless of this knob.
        self.save_folder = save_folder
        self.save_weight_folder = os.path.join(save_folder, "weights")
        self._async_saves = bool(async_checkpoint)
        self.checkpoints = CheckpointManager(
            self.save_weight_folder,
            save_best_for=save_best_for,
            async_save=False,
            max_to_keep=max_checkpoints_to_keep,
            fault_plan=fault_plan,
        )
        self.saver = AsyncCheckpointSaver(
            self.checkpoints, on_commit=self._on_async_commit
        )

        # Telemetry subsystem (ISSUE 4; docs/observability.md): structured
        # JSONL event log, goodput wall-time buckets, on-device train-health
        # stats (threaded into the engine below), per-window MFU, and anomaly
        # detectors. telemetry=None (default) is the historical program —
        # self.events is a disabled no-op, self.goodput stays None, and the
        # engine traces the exact pre-telemetry step. Constructed BEFORE the
        # mesh so the elastic-resume peek below (which may re-plan the mesh)
        # reports through the event log; the mesh-dependent peak-FLOPs figure
        # is finalized right after mesh selection.
        self.telemetry = resolve_telemetry(telemetry)
        if self.telemetry is not None:
            self.events = EventLog(
                self.telemetry.events_path
                or os.path.join(save_folder, "telemetry", "events.jsonl")
            )
            self.goodput = GoodputMeter() if self.telemetry.goodput else None
            self.anomaly_detector = self.telemetry.resolve_anomaly()
            self._flops_per_step = self.telemetry.flops_per_step
        else:
            self.events = EventLog(None)
            self.goodput = None
            self.anomaly_detector = None
            self._flops_per_step = None
        # Straggler attribution (ISSUE 13; telemetry/straggler.py): per-chip
        # arrival-skew fields sampled at the log_every syncs, the live
        # inputs to the doctor's `straggler` verdict. Off (or telemetry
        # off) keeps the sync path byte-identical to the historical one.
        self._straggler_on = self.telemetry is not None and getattr(
            self.telemetry, "straggler", False
        )
        # Attempt id (ISSUE 16): the monotonic per-run-dir restart
        # generation, claimed in train() (rank 0, telemetry on) and stamped
        # on run_start/heartbeat records + checkpoint meta so one appended
        # events.jsonl attributes every record to the attempt that wrote
        # it. 0 = unclaimed (telemetry off / non-zero rank).
        self._attempt = 0
        self._last_straggler: dict | None = None
        self._max_straggler_ratio: float | None = None
        # Live doctor signals (telemetry/doctor.py): per-kind anomaly
        # counts, hung steps, and steady-state retraces, accumulated where
        # the trainer already observes each fact — the epoch-end `doctor/*`
        # TensorBoard scalars project them through the same rules the
        # offline run doctor applies to the event log.
        self._anomaly_counts: dict[str, int] = {}
        self._hung_steps = 0
        self._late_compiles = 0
        # Epoch this attempt began at (set after restore in train()):
        # compiles there are warmup, not the compile_bound retrace signal.
        self._start_epoch = 0
        self._peak_flops = 0.0  # finalized after mesh selection below
        # Live-operations layer (ISSUE 15; docs/observability.md "Live
        # monitoring"): the heartbeat pulse + the optional in-process
        # status exporter. Heartbeats are emitted at the existing
        # log_every syncs (source="loop") and, when the step_timeout
        # watchdog is armed, from its patrol thread between syncs
        # (source="watchdog" + since_progress_s) — both debounced to
        # heartbeat_every_s through ONE lock-guarded gate (the patrol
        # thread and the loop race the debounce state, nothing else).
        self._heartbeat_every_s = (
            float(getattr(self.telemetry, "heartbeat_every_s", 0.0) or 0.0)
            if self.telemetry is not None
            else 0.0
        )
        self._hb_lock = threading.Lock()
        self._hb_last_emit = 0.0
        # The last sync point's progress fields, swapped wholesale under
        # the lock so a patrol-thread heartbeat reads one coherent dict
        # (its step fields may lag the hang by up to log_every steps; its
        # since_progress_s figure is exact — the watchdog measures it).
        self._hb_fields: dict = {}
        # Status exporter (telemetry/exporter.py): constructed in train()
        # on process 0 when Telemetry(export_port=...) asks for it. The
        # trainer BUILDS a fresh snapshot dict at its sync points and
        # swaps the reference; the exporter's HTTP threads only read
        # whichever complete dict the reference points at — the hot loop
        # is never blocked and never shares mutable state with a scrape.
        self.exporter = None
        self._status: dict = {}
        # Recovery skips (restore_latest_valid / the resume peek walking past
        # a corrupt checkpoint) land in the event log as `checkpoint_rejected`
        # records.
        self.checkpoints.event_log = self.events

        # Elastic resume (ISSUE 12; docs/fault_tolerance.md): resolve the
        # resume checkpoint BEFORE choosing the mesh. A sharded checkpoint
        # written on a different device count than this backend re-plans the
        # mesh axes + grad-accumulation for the current topology
        # (parallel.elastic) when mesh=None — a run killed at fsdp=8 resumes
        # on 4 or 16 devices without user intervention. Same-topology resumes
        # (and cold starts) are untouched: the peek is host-side metadata
        # reading only, and the historical program stays byte-identical.
        snapshot_path = self._peek_resume_checkpoint(snapshot_path, mesh, batch_size)
        if self._elastic_plan is not None:
            mesh = self._elastic_plan.mesh_config.build()

        # Mesh — the distributed world (replaces LOCAL_RANK/RANK/WORLD_SIZE
        # env reads + DDP wrap, ``:48-52``). mesh=None is the historical
        # pure-DP program (1-D data mesh over every device, replicated
        # params — trace_counts + params parity test-enforced); any
        # MeshConfig(...).build() mesh trains sharded end to end
        # (docs/parallelism.md): state initializes directly into the
        # fsdp/tensor layout, chained windows / checkpoints / preflight all
        # operate on the sharded arrays.
        self.mesh = mesh if mesh is not None else mesh_lib.create_mesh()
        self.world_size = self.mesh.devices.size
        # Batch-dim divisibility is against the BATCH-SHARDED axes product
        # (data x fsdp — parallel.mesh.batch_shard_extent), not the device
        # count: a data=2/tensor=4 mesh runs 2 batch shards on 8 devices,
        # and requiring batch % 8 == 0 would reject valid TP configs while
        # batch % 2 != 0 would fail deep in jax array assembly instead of
        # here with names attached.
        self.batch_replicas = mesh_lib.batch_shard_extent(self.mesh)
        if batch_size % self.batch_replicas:
            raise ValueError(
                f"global batch_size {batch_size} is not divisible by the "
                f"mesh's batch-shard extent {self.batch_replicas} (= product "
                "of the data and fsdp axes): every batch shard must hold the "
                "same number of rows. Round batch_size or re-plan the mesh."
            )
        # Elastic re-validation (ISSUE 12 satellite): a resumed run on a
        # re-planned (or hand-picked) mesh can land on a global batch the new
        # data x fsdp extent x accumulation does not tile — the engine's
        # microbatch reshape would then fail deep in jax array assembly. Fail
        # fast here with the ctor-style message instead.
        if self._topology_changed and batch_size % (
            self.batch_replicas * self.accum_steps
        ):
            suggestion = elastic_lib.nearest_divisible_accum(
                batch_size, self.batch_replicas, self.accum_steps
            )
            raise ValueError(
                f"global batch_size {batch_size} does not tile into "
                f"accum_steps={self.accum_steps} microbatches over the "
                f"resumed mesh's batch-shard extent {self.batch_replicas}: "
                "every microbatch shard must hold the same number of rows "
                f"(batch % (extent x accum) != 0). Nearest divisible "
                f"accum_steps: {suggestion}."
            )
        self.local_batch_size = batch_size // jax.process_count()
        # Parameter-sharding rules (parallel.sharding): "auto" resolves via
        # the build_sharding_rules hook AFTER build_model runs (the hook may
        # inspect self.model); an explicit list/None passes through. None on
        # a pure-DP mesh is the historical replicated program. Any OTHER
        # string is rejected here — forwarded to the engine it would crash
        # deep inside state_shardings as a bogus (regex, spec) iterable with
        # no mention of this knob.
        if isinstance(sharding_rules, str) and sharding_rules != "auto":
            raise ValueError(
                f"sharding_rules={sharding_rules!r}: the only string value is "
                "'auto' (resolve via build_sharding_rules). Pass None for the "
                "replicated/FSDP-fallback default, or an explicit list of "
                "(path_regex, PartitionSpec) rules."
            )
        self._sharding_rules_requested = sharding_rules
        self.fsdp_min_size = int(fsdp_min_size)

        # Telemetry's mesh-dependent piece (the subsystem itself was
        # constructed before mesh selection, for the elastic peek).
        if self.telemetry is not None:
            self._peak_flops = (
                telemetry_mfu.device_peak_flops(self.mesh.devices.flat[0])
                * self.mesh.devices.size
            )
        # Memory preflight (ISSUE 8; memory/preflight.py): predict the
        # configured program's peak HBM from an abstract lowering BEFORE the
        # first real compile, fail fast on predicted OOM with a batch/
        # microbatch recommendation. preflight=None (default) reproduces the
        # historical program exactly — no lowering, no probe, trace_counts +
        # params parity test-enforced (the telemetry/profile convention).
        self.preflight = resolve_preflight(preflight)
        self._preflight_done = False
        # The last PreflightReport (fit verdict, per-class attribution,
        # recommendations) — operator-inspectable after train().
        self.memory_report = None
        # Hot-path profiling capture (profiling/capture.py): one traced
        # window of real steps, driven at unit boundaries in train_epoch.
        # Rank-0 owned; events no-op when telemetry is off.
        self._profile_capture = (
            StepTraceCapture(
                self.profile,
                log=self.log,
                events=self.events,
                flops_source=self._profile_flops_index,
            )
            if self.profile is not None
            else None
        )
        # MFU probe bookkeeping: the first executed batch's abstract shapes
        # (ShapeDtypeStructs only — no device ops) feed the one-time
        # engine.step_cost_analysis probe at the end of the first epoch.
        self._mfu_probed = False
        self._abstract_batch = None
        self._last_step_ms = None
        # Loss-scale backoff detection reads the per-step `loss_scale` metric
        # at sync points (already host-fetched there — zero extra syncs).
        self._last_scale_seen = None

        # Build hooks (``:38-41``) — model/criterion first, then datasets
        # (so ``build_scheduler`` can size per-epoch schedules from
        # ``len(self.train_dataset)`` without re-scanning), then
        # schedule/optimizer/engine.
        self.model = self.build_model()
        self.criterion = self.build_criterion()

        # Datasets + loaders (``:56-71``).
        self.train_dataset = self.build_train_dataset()
        self.train_dataloader = self.build_dataloader(self.train_dataset, phase="train")
        # Streaming data plane (ISSUE 19; docs/data.md): duck-typed on the
        # reader-state surface so any build_dataloader override returning a
        # StreamingLoader gets checkpoint-carried reader state + the
        # shard_assignment/data_reader_state telemetry without trainer
        # subclassing. The loader feeds per-host row slices; telling it the
        # mesh's batch-shard extent pins its assignment version to the
        # data x fsdp split it actually feeds (PR 9) — which is what makes
        # an elastic N→M resume visible as a version change.
        self._streaming_train = hasattr(self.train_dataloader, "reader_state")
        if self._streaming_train and hasattr(self.train_dataloader, "batch_extent"):
            self.train_dataloader.batch_extent = self.batch_replicas
        self.val_dataloader = None
        if have_validate:
            self.val_dataset = self.build_val_dataset()
            self.val_dataloader = self.build_dataloader(self.val_dataset, phase="val")

        schedule = self.build_scheduler()
        if schedule is None:
            schedule = optax.constant_schedule(0.0)
        elif not callable(schedule):
            schedule = optax.constant_schedule(float(schedule))
        self.schedule = schedule
        self.optimizer = self.build_optimizer(self.schedule)

        self.sharding_rules = (
            self.build_sharding_rules()
            if isinstance(self._sharding_rules_requested, str)
            and self._sharding_rules_requested == "auto"
            else self._sharding_rules_requested
        )
        self.engine = TrainEngine(
            self.build_loss_fn(),
            self.optimizer,
            self.mesh,
            # self.accum_steps, not the ctor arg: an elastic re-plan may have
            # re-solved the factor for the new batch-shard extent.
            accum_steps=self.accum_steps,
            schedule=self.schedule,
            nan_guard=self.nan_policy in ("skip", "restore_last_good"),
            precision=self.precision,
            loss_scale=self._initial_loss_scale,
            stats=self.telemetry.stats if self.telemetry is not None else False,
            sharding_rules=self.sharding_rules,
            fsdp_min_size=self.fsdp_min_size,
        )

        # State init (replaces model.to(device) + DDP param broadcast).
        # Sharded init: init_state jits the model init with the engine's
        # state sharding as OUTPUT sharding, so fsdp/tensor-sharded params
        # materialize directly into their shards — a model too big for one
        # chip's HBM never exists replicated anywhere.
        example = self.build_example_input()
        self.state = self.engine.init_state(
            jax.random.key(seed),
            lambda rng: self.model.init(rng, example),
        )
        self._log_sharded_layout()

        # Snapshot resume (``:44-45,96-101``). The peek above already
        # resolved "latest_valid" to the newest checkpoint passing integrity
        # validation (falling back past a torn last save, emitting
        # `checkpoint_rejected` for each reject) — or to None on a cold
        # start — and read its meta.
        if snapshot_path is not None:
            t_restore = time.perf_counter()
            self.state, self.cur_epoch = self.checkpoints.restore(
                snapshot_path,
                self.state,
                # The peek's latest_valid resolution already hashed every
                # file; re-validating would double the resume disk reads.
                validate=not self._resume_prevalidated,
                # The peek inspected the recorded topology: a mismatch was
                # either re-planned (mesh=None) or explicitly overridden by
                # the user's mesh — both restore into a current-backend
                # layout, so the manager's topology seam may stand down.
                allow_topology_change=self._topology_changed,
            )
            meta = (
                self._resume_meta
                if self._resume_meta is not None
                else self.checkpoints.read_meta(snapshot_path)
            )
            self._resume_step_in_epoch = int(
                (meta.get("loop") or {}).get("step_in_epoch", 0)
            )
            # Streaming reader state (ISSUE 19): the checkpoint's data/ item
            # positions the data plane. Missing item = fresh cursor (a
            # pre-streaming checkpoint or a non-streaming run — the
            # loss-scale item rule); present = validate it speaks this
            # stream and position at cursor // G, O(1). The data cursor is
            # authoritative for the reader; it cross-checks the loop's
            # step_in_epoch (same quantity, saved atomically together).
            if self._streaming_train:
                data_state = self.checkpoints.read_data_state(snapshot_path)
                if data_state:
                    resume_batch = self.train_dataloader.apply_reader_state(
                        data_state
                    )
                    if resume_batch != self._resume_step_in_epoch:
                        self.log(
                            "checkpoint data cursor (batch "
                            f"{resume_batch}) disagrees with loop "
                            f"step_in_epoch ({self._resume_step_in_epoch}); "
                            "trusting the data cursor",
                            "warning",
                        )
                        self._resume_step_in_epoch = resume_batch
                else:
                    self.log(
                        "checkpoint has no data/ item (pre-streaming): "
                        "streaming reader resumes with a fresh cursor"
                    )
            if self.goodput is not None:
                # Cumulative goodput counters ride checkpoint meta (the way
                # loss_scale state rides its checkpoint item): a resumed run
                # continues the interrupted run's accounting bit-identically
                # (json round-trips floats exactly — test-enforced), then
                # books the restore itself as restart-rollback overhead.
                saved = (meta.get("telemetry") or {}).get("goodput")
                if saved:
                    self.goodput.load_state(saved)
                self.goodput.account(
                    "restart_rollback", time.perf_counter() - t_restore
                )
            self.events.emit(
                "checkpoint_restore",
                name=os.path.basename(str(snapshot_path)),
                epoch=self.cur_epoch,
                step_in_epoch=self._resume_step_in_epoch,
            )
            self._emit_elastic_restore(snapshot_path)
            self.log(
                f"Resumed from {snapshot_path} at epoch {self.cur_epoch}"
                + (
                    f", step {self._resume_step_in_epoch} (mid-epoch)"
                    if self._resume_step_in_epoch
                    else ""
                )
            )

    # ------------------------------------------------------------------
    # Framework-provided machinery (overridable, like ``build_dataloader``
    # at ``trainer/trainer.py:209-217``).
    # ------------------------------------------------------------------

    def build_dataloader(self, dataset, phase: str = "train") -> ShardedLoader:
        """Default loader: deterministic global shuffle for train (fixing the
        reference's cross-rank shuffle bug, SURVEY.md §2e), padded static-shape
        final batch for val."""
        train = phase == "train"
        return ShardedLoader(
            dataset,
            self.batch_size,
            shuffle=train,
            seed=self.seed,
            transform=getattr(dataset, "transform", None),
            # dataset.collate_fn (ref trainer/trainer.py:59-71) is picked up
            # by the ShardedLoader ctor's own fallback.
            num_workers=self.num_workers,
            prefetch_batches=self.prefetch_batches,
            drop_last=train,
            pad_final=not train,
            skip_corrupt=self.skip_corrupt_records,
        )

    def build_example_input(self) -> jax.Array:
        """A zero batch for Flax shape inference, derived from the first train
        record. Override for models whose input is not ``record['image']``."""
        record = self.train_dataset[0]
        image = record["image"]
        if self.train_dataloader.transform is not None:
            image = self.train_dataloader.transform(image, epoch=0, index=0)
        return jnp.zeros((1,) + tuple(np.shape(image)), jnp.float32)

    # ------------------------------------------------------------------
    # Train / validate loops
    # ------------------------------------------------------------------

    def train(self) -> None:
        """The epoch loop — structural twin of ``trainer/trainer.py:104-181``."""
        self._install_sigterm()
        self.metrics_writer.reopen()  # symmetric with the close() below
        self._start_epoch = self.cur_epoch  # warmup epoch for late-compile
        if self.goodput is not None:
            self.goodput.start()
        if self.events.enabled:
            # guarded like run_end: the field build includes an
            # int(self.state.step) device fetch the telemetry-off
            # (historical) path must not pay
            self._attempt = claim_attempt(self.save_folder)
            fields = dict(
                attempt=self._attempt,
                epoch=self.cur_epoch,
                max_epoch=self.max_epoch,
                step=int(self.state.step),
                resumed_step_in_epoch=self._resume_step_in_epoch,
                processes=jax.process_count(),
                devices=self.world_size,
                mesh={str(k): int(v) for k, v in self.mesh.shape.items()},
                batch_replicas=self.batch_replicas,
                chain_steps=self.chain_steps,
                compute_dtype=str(jnp.dtype(self.precision.compute_dtype)),
            )
            if self.goodput is not None:
                # Cumulative-counter snapshot (zero on a cold start, the
                # carried totals on a resume): the timeline exporter
                # anchors its goodput-span chain here, so the spans cover
                # exactly THIS attempt's wall.
                fields["goodput_seconds"] = self.goodput.to_state()
            # Provenance stamp (ISSUE 14): git SHA + jax/jaxlib + effective
            # XLA_FLAGS + the program identity, so run_compare can refuse
            # to diff runs that measured different programs. Inside the
            # events.enabled guard like the rest of the field build.
            from distributed_training_pytorch_tpu.telemetry.provenance import (
                provenance_fields,
            )

            fields["provenance"] = provenance_fields(
                mesh=fields["mesh"],
                dtype=fields["compute_dtype"],
                chain_steps=self.chain_steps,
                batch=self.batch_size,
            )
            self.events.emit("run_start", **fields)
            # Streaming shard assignment (ISSUE 19): one record per attempt
            # — on an elastic resume the loader's extent was re-planned
            # above, so the version/extent here IS the re-split assignment
            # (docs/data.md "elastic re-split ritual").
            if self._streaming_train and hasattr(self.train_dataloader, "assignment"):
                self.events.emit(
                    "shard_assignment",
                    elastic=self._topology_changed,
                    **self.train_dataloader.assignment(
                        cursor=self._resume_step_in_epoch
                        * self.train_dataloader.global_batch_size
                    ),
                )
            # Kernel-policy visibility (ISSUE 17): route ops/dispatch.py's
            # one-time kernel_dispatch decisions into this run's event log.
            # Decisions already made while building the model were buffered
            # by the dispatcher and flush here; uninstalled in the finally.
            from distributed_training_pytorch_tpu.ops import dispatch as _dispatch

            _dispatch.set_event_sink(self.events.emit)
        # Status exporter (ISSUE 15): rank-0 only, constructed per train()
        # attempt and torn down in the finally below. A taken port warns
        # and disables (never a reason training dies); the run itself is
        # bit-exact with export_port=None (the exporter only READS
        # host-side snapshots — test-enforced).
        if (
            self.telemetry is not None
            and self.telemetry.export_port is not None
            and jax.process_index() == 0
        ):
            from distributed_training_pytorch_tpu.telemetry.exporter import (
                StatusExporter,
            )

            self.exporter = StatusExporter(
                lambda: self._status,
                self.telemetry.export_port,
                log=lambda msg: self.log(msg, "warning"),
            )
        # Seed the liveness pulse: a monitor attaching before the first
        # log_every sync still sees a heartbeat (and the exporter serves a
        # pre-first-sync snapshot instead of an empty dict). `units` on
        # heartbeats counts executed units cumulatively across THIS
        # attempt (epochs reset `executed`; a liveness progress marker
        # must be monotone).
        self._attempt_units = 0
        self._note_heartbeat_progress(
            epoch=self.cur_epoch,
            step_in_epoch=self._resume_step_in_epoch,
            units=0,
        )
        self._emit_heartbeat("loop")
        self._update_status(step_in_epoch=self._resume_step_in_epoch, units=0)
        try:
            self._train_loop()
        finally:
            # Stop owning the process SIGTERM once training is over (or died):
            # a lingering handler would silently swallow later terminations.
            # Symmetric with the install above, so a re-entered train() is
            # protected again. The metrics writer closes here too so the
            # preemption early-return and error paths flush it.
            self._restore_sigterm()
            # Error/preemption paths must not leave a background commit in
            # flight into interpreter teardown; a commit error here must not
            # mask the original exception (logged, not raised). close() also
            # stops the commit worker — a process constructing many Trainers
            # must not accumulate parked daemon threads (a re-entered
            # train()'s next save restarts the worker transparently).
            self._flush_saver_logged()
            self.saver.close()
            if self.goodput is not None:
                self.goodput.stop()
            if self.events.enabled:
                from distributed_training_pytorch_tpu.ops import dispatch as _dispatch

                _dispatch.clear_event_sink()
                fields = {
                    "step": int(self.state.step),
                    "epoch": self.cur_epoch,
                    "preempted": self._preempted,
                    "nonfinite_steps": self.nonfinite_steps,
                }
                if self.goodput is not None:
                    fields["goodput"] = self.goodput.goodput
                    fields["goodput_seconds"] = self.goodput.to_state()
                    fields["goodput_fractions"] = self.goodput.fractions()
                if self.anomaly_detector is not None:
                    fields["anomalies"] = self.anomaly_detector.total_fired
                self.events.emit("run_end", **fields)
            # Final exporter snapshot (phase "finished"), then release the
            # port — a scraper that races the teardown gets either the
            # terminal snapshot or a connection refusal, never a hang. A
            # re-entered train() constructs a fresh exporter.
            self._update_status(phase="finished")
            if self.exporter is not None:
                self.exporter.close()
                self.exporter = None
            self.events.close()  # a re-entered train() lazily reopens (append)
            self.metrics_writer.close()

    def _train_loop(self) -> None:
        best_banner: dict | None = None
        for epoch in range(self.cur_epoch, self.max_epoch):
            self.cur_epoch = epoch

            # Periodic validation + best-model tracking at the top of the
            # epoch (``:114-135`` — validates *before* this epoch's training;
            # best stores label `epoch`, deliberate parity with §2e).
            if self.have_validate and self.save_period and epoch % self.save_period == 0:
                metrics = self.validate()
                if self._save_checkpoint(
                    BEST, epoch, reason="best", metrics=metrics, best=True
                ):
                    best_banner = {"epoch": epoch, "metrics": dict(metrics)}
                if best_banner is not None:
                    self.log(100 * "=")
                    msg = f"The BEST model is at EPOCH {best_banner['epoch']} and has "
                    for k, v in best_banner["metrics"].items():
                        msg += f" | {k.upper()} = {v} | "
                    self.log(msg)

            # Train one epoch (``:138-156``).
            self.train_dataloader.set_epoch(epoch)
            self.log(100 * "=")
            self.log(
                f"[process {jax.process_index()}] Epoch {epoch + 1}/{self.max_epoch}"
            )
            epoch_metrics = self.train_epoch(epoch)

            # Preemption: save a resumable snapshot and stop. An interrupted
            # epoch is labeled `epoch` (resume retrains it); a completed one
            # `epoch + 1` — same labeling rule as the normal saves below.
            # The decision is collective: a host whose signal arrived after
            # the last in-epoch poll must not diverge from its peers here.
            if self._collective_preempt_flag():
                self._preempted = True
                resume_epoch = epoch if self._epoch_interrupted else epoch + 1
                # A mid-epoch interruption records its position so the resume
                # skips the already-trained batches (bit-exact continuation);
                # an epoch-boundary save restarts the next epoch at step 0.
                loop_state = (
                    {"step_in_epoch": self._interrupted_at_step}
                    if self._epoch_interrupted
                    else None
                )
                self.events.emit(
                    "preemption",
                    epoch=epoch,
                    resume_epoch=resume_epoch,
                    step_in_epoch=self._interrupted_at_step
                    if self._epoch_interrupted
                    else 0,
                )
                self._save_checkpoint(
                    LAST, resume_epoch, loop_state=loop_state, wait=True,
                    reason="preemption",
                )
                self.log(
                    f"SIGTERM received — saved resumable snapshot (epoch "
                    f"{resume_epoch}"
                    + (
                        f", step {self._interrupted_at_step}"
                        if self._epoch_interrupted
                        else ""
                    )
                    + f") to {self.checkpoints.path(LAST)}; exiting",
                    "warning",
                )
                return

            # Next-LR report (``:159-160``) — optax schedules are per-step.
            next_lr = float(self.schedule(self.state.step))
            self.log(f"THE NEXT LEARNING RATE VALUE IS {next_lr}")

            # last / periodic checkpoint (``:163-172``): saved epoch is
            # epoch+1 = the next epoch to train on resume (``:165-167``).
            if self.have_validate:
                if (epoch + 1) % self.last_save_period == 0 or epoch + 1 == self.max_epoch:
                    self._save_checkpoint(LAST, epoch + 1)
                    self.log(f"Saved model at epoch {epoch + 1}!")
            elif self.save_period and epoch % self.save_period == 0:
                self._save_checkpoint(epoch_checkpoint_name(epoch + 1), epoch + 1)
                self.log(f"Saved model at epoch {epoch + 1}!")

            # Epoch loss report — *global* means (pmean'd inside the step),
            # upgrading the reference's local-only report (``:175-178``).
            msg = "TOTAL GLOBAL TRAINING LOSS: "
            for k, v in epoch_metrics.items():
                msg += f" | {k} = {v} | "
            self.log(msg)
            self.metrics_writer.write(int(self.state.step), epoch_metrics, prefix="train")
            self._write_precision_scalars()
            self._write_telemetry_scalars()

        # Barrier: every queued background commit fully on disk (and any
        # commit error surfaced) before the run declares itself finished.
        # The wait IS checkpoint stall — the hot loop is over, but the run
        # cannot end until the commits land — so it books to `checkpoint`,
        # not `other`: a commit backlog (slow filesystem, commit_delay_s
        # chaos seam) must show up where the doctor's checkpoint-stall
        # verdict looks, not vanish into epoch glue.
        if self.goodput is not None:
            self.goodput.tick("other")
        self.saver.flush()
        if self.goodput is not None:
            self.goodput.tick("checkpoint")
        self.log("Finished!")

    def _log_sharded_layout(self) -> None:
        """One construction-time line saying what the mesh actually did to
        the state: how many leaves landed sharded, and the per-device vs
        global param bytes (the measurable ZeRO-3 win). Silent on a pure-DP
        mesh — the historical console transcript is part of the historical
        program."""
        from distributed_training_pytorch_tpu.parallel import sharding as sharding_lib

        record = sharding_lib.sharding_record(self.state)
        if record is None:
            return
        n_sharded = len(record["specs"])
        # Denominator over the SAME tree the record scanned (the full
        # state): a sharded model_state leaf must not produce a >100%
        # fraction against a params+opt_state-only count.
        n_leaves = len(jax.tree.leaves(self.state))
        global_bytes = sharding_lib.tree_shard_bytes(
            self.state.params, jax.sharding.SingleDeviceSharding(jax.devices()[0])
        )
        per_device = sharding_lib.tree_shard_bytes(self.state.params)
        self.log(
            f"mesh {record['mesh']}: {n_sharded}/{n_leaves} state leaves "
            f"sharded; per-device param bytes {int(per_device)} "
            f"(global {int(global_bytes)})"
        )

    # ------------------------------------------------------------------
    # Elastic resume (ISSUE 12; docs/fault_tolerance.md "Elastic training")
    # ------------------------------------------------------------------

    def _peek_resume_checkpoint(self, snapshot_path, mesh, batch_size):
        """Resolve the resume checkpoint BEFORE the mesh is chosen.

        Returns the concrete checkpoint name/path to restore (or None for a
        cold start), maps ``"latest_valid"`` to the newest checkpoint passing
        integrity validation (the exact choice the restore will make —
        rejects emit ``checkpoint_rejected``), and reads its meta once (the
        restore site reuses it). When the recorded sharding topology
        disagrees with ``jax.device_count()``:

        * ``mesh=None`` — re-plan via :mod:`parallel.elastic`: the solved
          :class:`MeshConfig` replaces the default mesh and
          ``self.accum_steps`` is re-solved so the global batch math stays
          equivalent (``self._elastic_plan`` records the decision);
        * an explicit ``mesh`` — honored verbatim (the user already chose a
          current-backend layout); only the topology-change flag is set so
          the manager's :class:`TopologyMismatchError` seam stands down.

        Same-topology resumes and cold starts set nothing — the historical
        program is untouched (host-side metadata reads only).
        """
        self._elastic_plan = None
        self._resume_meta = None
        self._resume_prevalidated = False
        self._topology_changed = False
        if snapshot_path is None:
            return None
        if snapshot_path == "latest_valid":
            if not self.checkpoints.checkpoint_names():
                # The automatic-restart entry point must be idempotent: on
                # the very first launch there is nothing to resume.
                self.log("no checkpoint to resume (latest_valid) — starting fresh")
                return None
            name = self.checkpoints.latest_valid_name()
            if name is None:
                # Same diagnostic the manager's restore_latest_valid raises:
                # name every checkpoint the walk rejected.
                raise CheckpointError(
                    f"no valid checkpoint under {self.checkpoints.directory} "
                    f"(invalid/corrupt: {self.checkpoints.checkpoint_names() or 'none found'})"
                )
            self._resume_prevalidated = True
            snapshot_path = name
        try:
            self._resume_meta = self.checkpoints.read_meta(snapshot_path)
        except Exception:  # noqa: BLE001 — the restore below raises the
            return snapshot_path  # canonical corrupt/missing error instead
        record = self._resume_meta.get("sharding")
        if not record:
            return snapshot_path  # pure-DP / pre-sharding: nothing to re-plan
        saved_axes = elastic_lib.record_axes(record)
        saved_devices = elastic_lib.axes_device_product(saved_axes)
        if saved_devices == jax.device_count():
            return snapshot_path
        self._topology_changed = True
        ckpt = os.path.basename(str(snapshot_path))
        if mesh is not None:
            self.log(
                f"resume checkpoint {ckpt!r} was written on {saved_devices} "
                f"devices (mesh {saved_axes}); this backend has "
                f"{jax.device_count()} — honoring the explicitly passed mesh "
                "(no re-plan; accumulation unchanged)."
            )
            return snapshot_path
        self._elastic_plan = elastic_lib.replan(
            saved_axes,
            jax.device_count(),
            batch_size=batch_size,
            accum_steps=self.accum_steps,
        )
        self.accum_steps = self._elastic_plan.accum_steps
        self.log(
            f"elastic restore: checkpoint {ckpt!r} was written on "
            f"{saved_devices} devices (mesh {saved_axes}); re-planned for "
            f"{jax.device_count()} devices as mesh "
            f"{self._elastic_plan.new_axes} with accum_steps="
            f"{self.accum_steps} (was {self._elastic_plan.old_accum_steps}) "
            "— same effective global batch."
        )
        return snapshot_path

    def _emit_elastic_restore(self, snapshot_path) -> None:
        """One ``elastic_restore`` flight record per topology-changed resume
        (docs/observability.md): old/new mesh axes and device counts, the
        old/new accumulation factors, and the re-plan reason."""
        if not self._topology_changed:
            return
        plan = self._elastic_plan
        if plan is not None:
            fields = plan.event_fields()
        else:
            record = (self._resume_meta or {}).get("sharding") or {}
            old_axes = elastic_lib.record_axes(record)
            fields = {
                "from_mesh": old_axes,
                "to_mesh": {str(k): int(v) for k, v in self.mesh.shape.items()},
                "from_devices": elastic_lib.axes_device_product(old_axes),
                "to_devices": jax.device_count(),
                "old_accum_steps": self.accum_steps,
                "accum_steps": self.accum_steps,
                "reason": "explicit mesh (no re-plan)",
            }
        self.events.emit(
            "elastic_restore",
            name=os.path.basename(str(snapshot_path)),
            replanned=plan is not None,
            **fields,
        )

    @property
    def model_dtype(self):
        """The activation dtype matching this trainer's precision policy —
        pass as ``dtype=`` when constructing models in ``build_model`` so
        model-internal casts agree with the policy's boundary casts
        (``jnp.float32`` under the default fp32 policy: identical models)."""
        return self.precision.compute_dtype

    def _write_precision_scalars(self) -> None:
        """TensorBoard observability for dynamic loss scaling: the current
        scale and the cumulative overflow-skip count, next to the train
        scalars. No-op (like every MetricsWriter call) without tensorboardX
        or off process 0; no-op entirely unless a DynamicScale is active."""
        scale_state = getattr(self.state, "loss_scale", None)
        if not is_dynamic(scale_state):
            return
        self.metrics_writer.write(
            int(self.state.step),
            {
                "loss_scale": float(scale_state.scale),
                "skipped_steps": float(scale_state.skipped_steps),
            },
            prefix="precision",
        )

    # ------------------------------------------------------------------
    # Telemetry (ISSUE 4; docs/observability.md). Everything here is a
    # no-op / zero-overhead path when telemetry is off, and never a reason
    # training dies (the MFU probe degrades to a warning on failure).
    # ------------------------------------------------------------------

    def _telemetry_meta(self) -> dict | None:
        """Cumulative telemetry counters for checkpoint meta — the goodput
        buckets (so goodput accounting survives kill/resume) plus the
        attempt id that wrote the checkpoint (ISSUE 16 provenance; the
        manager hoists it to a first-class ``meta["attempt"]``)."""
        meta = {}
        if self.goodput is not None:
            meta["goodput"] = self.goodput.to_state()
        if self._attempt:
            meta["attempt"] = self._attempt
        return meta or None

    def _flush_saver_logged(self) -> None:
        """Flush the async saver, reporting — never raising — a background
        commit failure. For the paths where an exception would defeat the
        path's own purpose: teardown (masking the original error), the
        emergency-save exit (aborting the grace-window shutdown), and the
        nan rollback (dying instead of degrading)."""
        err = self.saver.flush(raise_errors=False)
        if err is not None:
            self.log(f"background checkpoint commit failed: {err}", "error")

    def _on_async_commit(self, name: str, seconds: float) -> None:
        """Background-commit completion callback (runs on the saver's worker
        thread): book the commit's wall time to the ``checkpoint_async``
        goodput bucket — time the hot loop did NOT stall for — and leave a
        ``checkpoint_commit`` record in the flight log. Both sinks are
        thread-safe (``GoodputMeter.account`` touches a bucket the tick
        stream never writes; ``EventLog.emit`` locks)."""
        if self.goodput is not None:
            self.goodput.account("checkpoint_async", seconds)
        self.events.emit("checkpoint_commit", name=name, commit_ms=seconds * 1e3)

    def _save_checkpoint(
        self,
        name: str,
        epoch: int,
        *,
        loop_state: Mapping | None = None,
        wait: bool = False,
        reason: str = "epoch",
        metrics: Mapping | None = None,
        best: bool = False,
    ) -> bool:
        """Checkpoint save + telemetry, one implementation for every trainer
        save site (last / periodic / preemption / best).

        Two modes (docs/fault_tolerance.md state machine):

        * **async** (``async_checkpoint=True`` and ``wait=False`` — the
          periodic/best saves): device->host snapshot on this thread, commit
          on the saver's background thread. Only the snapshot stall lands in
          the ``checkpoint`` goodput bucket; the background commit books
          itself to ``checkpoint_async`` via ``_on_async_commit``.
        * **emergency** (``wait=True`` — preemption and watchdog saves, or
          ``async_checkpoint=False``): flush any in-flight background save
          (completing it, never abandoning it), then commit synchronously —
          the save must be durable inside the eviction grace window. The
          full wall time is hot-loop stall, booked to ``checkpoint``.

        ``best=True`` routes through the manager's best-fitness rule;
        returns whether a checkpoint was written."""
        if self.goodput is not None:
            self.goodput.tick("other")  # close the epoch-glue interval
        mode = "async" if (self._async_saves and not wait) else "sync"
        telemetry_meta = self._telemetry_meta()
        # Streaming reader state rides EVERY save (sync/async/emergency/best
        # — this is the one save site): epoch is the resume epoch the caller
        # passed, cursor the global records already consumed in it (0 for an
        # end-of-epoch save; step_in_epoch * G for a preemption save).
        data_state = None
        if self._streaming_train:
            data_state = self.train_dataloader.reader_state(
                epoch=epoch,
                batches_consumed=int((loop_state or {}).get("step_in_epoch", 0)),
            )
        snapshot_s = None
        save_s = None  # full synchronous-save stall (the sync-mode twin of
        #                snapshot_s) — the timeline's `save:` span duration
        if best:
            if mode == "async":
                saved, snapshot_s = self.saver.maybe_save_best(
                    metrics, self.state, epoch, telemetry=telemetry_meta,
                    data_state=data_state,
                )
            else:
                t_save = time.perf_counter()
                saved = self.checkpoints.maybe_save_best(
                    metrics, self.state, epoch, telemetry=telemetry_meta,
                    data_state=data_state,
                )
                save_s = time.perf_counter() - t_save
        else:
            if mode == "async":
                snapshot_s = self.saver.save_async(
                    name, self.state, epoch, metrics=metrics,
                    loop_state=loop_state, telemetry=telemetry_meta,
                    data_state=data_state,
                )
            else:
                save_s = self.saver.save_sync(
                    name, self.state, epoch, metrics=metrics,
                    loop_state=loop_state, telemetry=telemetry_meta,
                    data_state=data_state,
                )
            saved = True
        if wait:
            # The emergency save above is already durable; a PRIOR background
            # commit's failure (re-stashed by save_sync) must be reported,
            # not abort the grace-window exit this save exists to protect.
            self._flush_saver_logged()
        if self.goodput is not None:
            self.goodput.tick("checkpoint" if saved else "other")
        if saved:
            fields = {"name": name, "epoch": epoch, "reason": reason, "mode": mode}
            if snapshot_s is not None:
                fields["snapshot_ms"] = snapshot_s * 1e3
            elif save_s is not None:
                fields["save_ms"] = save_s * 1e3
            if loop_state:
                fields["step_in_epoch"] = int(loop_state.get("step_in_epoch", 0))
            self.events.emit("checkpoint_save", **fields)
            if data_state is not None:
                # The data plane's save record (ISSUE 19): which records a
                # resume from this checkpoint will consume next.
                self.events.emit(
                    "data_reader_state",
                    name=name,
                    reason=reason,
                    epoch=int(data_state["epoch"]),
                    cursor=int(data_state["cursor"]),
                    seed=int(data_state["seed"]),
                    record_count=int(data_state["record_count"]),
                    assignment_version=int(data_state["assignment_version"]),
                )
        return saved

    def _write_telemetry_scalars(self) -> None:
        """TensorBoard: goodput fractions + per-step wall time / MFU next to
        the train scalars (process 0 only; no-op without tensorboardX —
        the MetricsWriter contract). The on-device health stats need no
        writer of their own: they are ordinary train metrics."""
        if self.telemetry is None:
            return
        step = int(self.state.step)
        if self.goodput is not None:
            self.metrics_writer.write(step, self.goodput.fractions(), prefix="goodput")
        if self._last_step_ms is not None:
            scalars = {"step_ms": self._last_step_ms}
            mfu = telemetry_mfu.mfu_value(
                self._flops_per_step or 0.0, self._last_step_ms / 1e3, self._peak_flops
            )
            if mfu is not None:
                scalars["mfu"] = mfu
            self.metrics_writer.write(step, scalars, prefix="telemetry")
        if self._last_straggler:
            self.metrics_writer.write(
                step,
                {
                    "skew_ms": self._last_straggler["chip_skew_ms"],
                    "ratio": self._last_straggler["straggler_ratio"],
                },
                prefix="straggler",
            )
        # The live doctor (ISSUE 13): the same verdict rules the offline
        # run doctor applies to the event log, projected from this run's
        # in-memory counters — dashboards see per-verdict severity scores
        # (>= 1.0 = over the line) without waiting for the offline pass.
        self.metrics_writer.write(
            step, telemetry_doctor.scalar_fields(self._doctor_signals()), prefix="doctor"
        )

    def _doctor_signals(self) -> "telemetry_doctor.Signals":
        """The live-path :class:`telemetry.doctor.Signals` bundle — the same
        facts :func:`telemetry.doctor.extract_signals` would distill from
        this run's event log, read off the trainer's own counters instead
        (no file round trip at epoch end)."""
        return telemetry_doctor.Signals(
            goodput_seconds=self.goodput.to_state() if self.goodput else None,
            anomaly_counts=dict(self._anomaly_counts),
            hung_steps=self._hung_steps,
            max_straggler_ratio=self._max_straggler_ratio,
            late_compiles=self._late_compiles,
        )

    def _emit_heartbeat(self, source: str, **extra) -> None:
        """The liveness pulse (ISSUE 15): one cheap ``heartbeat`` record,
        debounced to ``heartbeat_every_s`` across BOTH sources (the
        log_every sync and the watchdog patrol thread share one gate —
        the contract is "the log pulses at least this often while the
        process lives", not one pulse per source). Carries the last sync
        point's progress fields plus the cumulative goodput snapshot;
        zero device syncs (host counters and an allocator-free dict
        build only)."""
        if not self._heartbeat_every_s or not self.events.enabled:
            return
        now = time.monotonic()
        with self._hb_lock:
            if now - self._hb_last_emit < self._heartbeat_every_s:
                return
            self._hb_last_emit = now
            fields = dict(self._hb_fields)
        fields.update(extra)
        if self._attempt:
            fields["attempt"] = self._attempt
        if self.goodput is not None:
            # GoodputMeter's bucket keys are fixed at construction, so a
            # patrol-thread read races only float value updates — safe.
            fields["goodput_seconds"] = self.goodput.to_state()
        self.events.emit("heartbeat", source=source, **fields)

    def _note_heartbeat_progress(self, **fields) -> None:
        """Refresh the progress fields patrol-thread heartbeats report
        (one dict swap under the heartbeat lock)."""
        with self._hb_lock:
            self._hb_fields = dict(fields)

    def _heartbeat_patrol(self, since_progress_s: float) -> None:
        """Watchdog patrol-thread hook (``StepWatchdog(on_patrol=...)``):
        keep the event log pulsing while the main thread is stuck inside
        a step — ``since_progress_s`` (seconds since the last completed
        unit) is exactly what lets the monitor call the run *hung* rather
        than merely slow, and the record's continued arrival is what
        distinguishes hung from *dead*."""
        self._emit_heartbeat("watchdog", since_progress_s=since_progress_s)

    def _update_status(self, **extra) -> None:
        """Rebuild the exporter's status snapshot from the live counters
        (called at the existing sync points only — never the hot path).
        One reference assignment publishes it; HTTP threads read the
        complete dict it points at (``telemetry/exporter.py``)."""
        if self.exporter is None or not self.exporter.enabled:
            return
        sig = self._doctor_signals()
        scores = telemetry_doctor.scalar_fields(sig)
        verdict, worst = "healthy", 0.0
        for kind, score in scores.items():
            if kind != "healthy" and score >= 1.0 and score > worst:
                verdict, worst = kind, score
        snap = {
            "run_dir": self.save_folder,
            "pid": os.getpid(),
            "t_wall": time.time(),
            "phase": "training",
            "epoch": self.cur_epoch,
            "nonfinite_steps": self.nonfinite_steps,
            "hung_steps": self._hung_steps,
            "late_compiles": self._late_compiles,
            "anomaly_counts": dict(self._anomaly_counts),
            "doctor_scores": scores,
            "verdict": verdict,
        }
        if self.goodput is not None:
            snap["goodput_seconds"] = self.goodput.to_state()
            snap["goodput_fractions"] = self.goodput.fractions()
            snap["steady_fractions"] = telemetry_doctor.steady_fractions(
                snap["goodput_seconds"]
            )
        if self._last_step_ms is not None:
            snap["step_ms"] = self._last_step_ms
            mfu = telemetry_mfu.mfu_value(
                self._flops_per_step or 0.0,
                self._last_step_ms / 1e3,
                self._peak_flops,
            )
            if mfu is not None:
                snap["mfu"] = mfu
        snap.update(extra)
        self._status = snap

    def _maybe_probe_mfu(self) -> None:
        """One-time XLA cost-analysis probe for the per-step FLOP count
        (``TrainEngine.step_cost_analysis``): one extra off-hot-path compile
        that never touches the dispatch executables or ``trace_counts``.
        Runs at the end of the first trained epoch (shapes known by then);
        skipped when an analytic ``Telemetry(flops_per_step=...)`` was given,
        when MFU is off, or when a custom ``train_step`` override means the
        engine's step is not the one actually running."""
        if (
            self.telemetry is None
            or not self.telemetry.mfu
            or self._mfu_probed
            or self._flops_per_step is not None
            or self._abstract_batch is None
            or type(self).train_step is not Trainer.train_step
        ):
            return
        self._mfu_probed = True
        if self.engine.accum_steps > 1:
            # XLA's cost_analysis may count the grad-accumulation scan BODY
            # once (~accum x undercount — bench.py rescales against its
            # analytic anchor; the trainer has none, and a silently-wrong
            # MFU is worse than no MFU). Probe disabled: pass the analytic
            # count via Telemetry(flops_per_step=...) instead.
            self.log(
                "telemetry: MFU probe skipped under grad accumulation "
                f"(accum_steps={self.engine.accum_steps}) — XLA may count the "
                "microbatch scan body once; pass Telemetry(flops_per_step=...) "
                "for MFU reporting",
                "warning",
            )
            return
        t0 = time.perf_counter()
        try:
            cost = self.engine.step_cost_analysis(self.state, self._abstract_batch)
        except Exception as e:  # noqa: BLE001 — telemetry must never kill a run
            self.log(
                f"telemetry: MFU probe failed ({e}) — per-window MFU disabled",
                "warning",
            )
            return
        dt = time.perf_counter() - t0
        if self.goodput is not None:
            self.goodput.tick("compile")  # the probe IS an XLA compile
        self._flops_per_step = float(cost.get("flops", 0.0)) or None
        self.events.emit(
            "compile",
            kind="mfu_probe",
            seconds=dt,
            flops_per_step=self._flops_per_step,
        )

    def _run_memory_preflight(self, n: int, batch, *, can_chain: bool) -> None:
        """One-shot OOM preflight on the first execution unit's abstract
        shapes (``memory.preflight.run_preflight``): predicted peak vs
        per-device capacity, a ``memory_preflight`` event, and on predicted
        OOM a fail-fast :class:`~memory.PreflightOOMError` carrying the
        max-batch / microbatch recommendations. ``can_chain`` gates the
        chained-window prediction (the caller knows whether a full window
        can still occur this epoch — conservative at window granularity:
        lead-single realignment may rarely leave the last possible window
        unformed, in which case the verdict covers a slightly larger
        program than dispatches). Skipped (with a warning) under a custom
        ``train_step`` override — the engine's program is not the one
        dispatched, so its prediction would be for the wrong program (the
        MFU-probe rule)."""
        self._preflight_done = True
        if type(self).train_step is not Trainer.train_step:
            self.log(
                "memory preflight skipped: custom train_step override — the "
                "engine program the preflight would lower is not the one "
                "this trainer dispatches",
                "warning",
            )
            return
        per_step = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape if n == 1 else x.shape[1:], x.dtype
            ),
            batch,
        )
        self.memory_report = run_preflight(
            self.engine,
            self.state,
            per_step,
            self.preflight,
            chain_length=self.chain_steps if can_chain else None,
            log=self.log,
            events=self.events,
        )

    def _live_memory_fields(self) -> dict:
        """Per-window live device memory (``memory.live`` — the one
        memory_stats read): ``live_bytes``/``peak_bytes`` plus per-chip
        skew on multi-chip hosts. Read only at existing host sync points
        (an allocator query, zero device syncs); ``{}`` on statless
        backends — the records simply omit the fields. ``peak_bytes`` is
        the allocator's process-lifetime high-water mark (documented
        caveat): the per-window signal — and the growth detector's input —
        is ``live_bytes``."""
        if self.telemetry is None or not getattr(self.telemetry, "memory", True):
            return {}
        return window_memory_fields()

    def _profile_flops_index(self):
        """Per-op roofline join table for the profile capture's top-op rows
        (``profiling.report.flops_index`` over the engine's observability
        probe — same one-time off-hot-path compile discipline as the MFU
        probe: dispatch executables and ``trace_counts`` untouched). Returns
        None (rows carry no FLOPs/bytes) before the first batch's shapes are
        known or when the probe's module is not the program that was traced:
        a custom ``train_step`` override, or ``chain_steps > 1`` — the trace
        then covers the chained-scan executable, whose per-module instruction
        numbering does not line up with the single-step probe's, and a
        name-keyed join would attach a DIFFERENT instruction's flops/bytes to
        a colliding low-numbered name (confidently wrong roofline columns are
        worse than none)."""
        if (
            self._abstract_batch is None
            or self.chain_steps > 1
            or type(self).train_step is not Trainer.train_step
        ):
            return None
        from distributed_training_pytorch_tpu.profiling.report import flops_index

        return flops_index(
            self.engine.compile_step_probe(self.state, self._abstract_batch)
        )

    def _report_anomalies(self, anomalies, *, epoch=None, step_in_epoch=None) -> None:
        """Emit + log each finding; raise when the detector was built with
        ``action="raise"`` (the observability analog of nan_policy='raise')."""
        if not anomalies:
            return
        for a in anomalies:
            self._anomaly_counts[a.kind] = self._anomaly_counts.get(a.kind, 0) + 1
            self.events.emit(
                "anomaly",
                kind=a.kind,
                value=a.value,
                baseline=a.baseline,
                factor=a.factor,
                epoch=epoch,
                step_in_epoch=step_in_epoch,
            )
            self.log(f"telemetry anomaly: {a.describe()}", "warning")
        if self.anomaly_detector.action == "raise":
            from distributed_training_pytorch_tpu.telemetry import AnomalyError

            raise AnomalyError("; ".join(a.describe() for a in anomalies))

    def _validate_chain_config(self) -> None:
        """Reject/round knob combinations that would silently misalign with
        chained-window execution — fail loudly at construction, not as a
        drifted log cadence or a preemption poll that never fires."""
        if self.chain_steps < 1:
            raise ValueError(f"chain_steps must be >= 1, got {self.chain_steps}")
        if self.chain_steps == 1:
            return
        if type(self).train_step is not Trainer.train_step:
            raise ValueError(
                "chain_steps > 1 requires the engine-backed default train_step: "
                f"{type(self).__name__} overrides train_step, which executes "
                "per-step Python the chained device program cannot call. Keep "
                "chain_steps=1, or move the customization into build_loss_fn "
                "(traced into the compiled step, chains fine)."
            )
        if self.log_every and self.log_every % self.chain_steps:
            raise ValueError(
                f"log_every ({self.log_every}) must be a multiple of "
                f"chain_steps ({self.chain_steps}): intra-epoch loss syncs "
                "happen at window boundaries, so a non-multiple would silently "
                "drift the log cadence. Round log_every or chain_steps."
            )
        if self.preemption_check_every and self.preemption_check_every % self.chain_steps:
            rounded = (
                -(-self.preemption_check_every // self.chain_steps) * self.chain_steps
            )
            self.log(
                f"preemption_check_every={self.preemption_check_every} is not a "
                f"multiple of chain_steps={self.chain_steps} — rounded up to "
                f"{rounded} so multi-host preemption votes land on window "
                "boundaries (they cannot fire mid-window).",
                "warning",
            )
            self.preemption_check_every = rounded
        if self.step_timeout:
            self.log(
                f"chain_steps={self.chain_steps}: the hung-step watchdog pats "
                f"once per window, so its effective timeout scales to "
                f"step_timeout x chain_steps = {self.step_timeout * self.chain_steps}s."
            )

    def _chain_lead_singles(self, skip_steps: int) -> int:
        """Single steps to run before the first chained window of an epoch:
        realigns a mid-epoch resume offset to a window boundary (windows sit
        at absolute step_in_epoch multiples of chain_steps, so chained and
        resumed runs execute identical window shapes), and keeps the profiled
        prefix of the first epoch on the per-step path (the profiler brackets
        individual steps; its stop check fires at step 1 + profile_steps)."""
        first_window_step = skip_steps
        # skip_steps <= 1: _maybe_profile only ever STARTS a trace at
        # step_in_epoch == 1, so a deeper mid-epoch resume cannot profile
        # this epoch — extending its single-step prefix would waste
        # dispatches without a trace to show for it.
        if self.profile_dir is not None and not self._profiled and skip_steps <= 1:
            first_window_step = max(first_window_step, 2 + self.profile_steps)
        aligned = -(-first_window_step // self.chain_steps) * self.chain_steps
        return aligned - skip_steps

    def _fault_active_in_window(self, epoch: int, start: int, stop: int) -> bool:
        return self.fault_plan is not None and self.fault_plan.active_in_window(
            epoch, start, stop
        )

    def _pat_watchdog(self, watchdog, timeout):
        """Arm (first completed step only — the first step includes XLA
        compilation, minutes for a real model: arming before it would SIGTERM
        mid-compile and the resumed run would recompile and die the same way,
        a restart livelock) and pat the hung-step watchdog."""
        if not timeout:
            return watchdog
        if watchdog is None:
            # max_fires=2: fire 1 = graceful SIGTERM save; fire 2 = the
            # thread is wedged, hard-exit (_on_hung_step). The patrol hook
            # keeps heartbeats flowing from the watchdog thread while the
            # main thread is stuck (ISSUE 15 liveness contract).
            watchdog = StepWatchdog(
                timeout,
                self._on_hung_step,
                max_fires=2,
                on_patrol=(
                    self._heartbeat_patrol
                    if self._heartbeat_every_s and self.events.enabled
                    else None
                ),
            ).start()
        watchdog.pat()
        return watchdog

    def train_epoch(self, epoch: int) -> dict:
        """Inner hot loop: compiled step per global batch — or, with
        ``chain_steps > 1``, ONE compiled program per window of chain_steps
        batches (``engine.train_steps_chained``), removing per-step host
        dispatch entirely. Metrics stay device-resident either way (no
        per-step host sync — the reference pays a ``loss.item()`` sync every
        step, ``example_trainer.py:89``); chained windows return per-step
        metrics as scan outputs, so the accounting below is identical.

        Mid-epoch resume: when this epoch was interrupted by a preemption
        save at step k, the first k batches are skipped (the loader's
        permutation and the per-(epoch, index) augmentation keys are
        deterministic, so the surviving stream is identical to the one the
        interrupted run would have seen) — the resumed run stays bit-exact
        with an uninterrupted one. Under chaining the first (-k mod
        chain_steps) resumed steps run single-step so window boundaries
        realign to the uninterrupted run's."""
        # Metric records: (k, tree) where k == 1 holds one step's scalar
        # metrics and k > 1 a whole window's stacked scan outputs. Kept
        # UNsliced on purpose: per-step slicing here would issue k x num_keys
        # tiny device ops right after the one chained dispatch — paying back
        # the very dispatch overhead chaining removes. Slicing happens where
        # a host sync exists anyway (log points, epoch end).
        collected: list[tuple[int, Any]] = []
        skip_steps = self._resume_step_in_epoch
        self._resume_step_in_epoch = 0  # consumed by the first trained epoch
        step_in_epoch = skip_steps
        executed = 0
        synced_entries = 0  # index into `collected` of the last nan-policy sync
        synced_steps = 0  # the same sync position, in steps
        t0 = time.perf_counter()
        # Telemetry (no-ops when off): goodput attributes the epoch's wall
        # time to buckets at the loop's existing boundaries — no added device
        # syncs anywhere in this method; tele_sync anchors per-window step
        # timing at the log_every host syncs.
        tm = self.goodput
        if tm is not None:
            tm.tick("other")  # close the epoch preamble (validation/log glue)
        # The first fetch after a mid-epoch resume replays the loader past
        # the already-trained batches — restart-rollback cost, not data_wait.
        rollback_fetch = skip_steps > 0
        tele_sync = [t0, 0]  # (perf_counter, executed) at the last sync point
        trace_base = [0]  # trace_counts total before the in-flight unit
        # Trace totals at the last sync point / epoch start: a window (or
        # epoch) that paid XLA compile has a known-skewed wall, so its
        # step_time is withheld from the anomaly detector's EWMA — the
        # compile-polluted first windows would otherwise seed the baseline
        # minutes high and mask real regressions for the rest of the run
        # (warmup alone only delays firing; it does not keep the poison
        # out of the baseline).
        sync_trace = [sum(self.engine.trace_counts.values())]
        epoch_trace_start = sync_trace[0]
        num_batches = len(self.train_dataloader)
        chain = self.chain_steps
        # Resume skip happens at the loader's INDEX level when it can
        # (iter_batches: none of the skipped batches are read or decoded);
        # generic iterables fall back to drain-and-discard.
        if skip_steps and hasattr(self.train_dataloader, "iter_batches"):
            source_iter = self.train_dataloader.iter_batches(skip_steps)
        elif skip_steps:
            import itertools

            source_iter = itertools.islice(iter(self.train_dataloader), skip_steps, None)
        else:
            source_iter = iter(self.train_dataloader)
        host_batches = (
            self._check_image_range(self.preprocess_batch(b)) for b in source_iter
        )
        # Execution units (n, batch): n == chain -> a chain-stacked window,
        # n == 1 -> a plain single-step batch (lead realignment + epoch tail).
        if chain > 1:
            units = device_prefetch_chained(
                host_batches,
                self.mesh,
                chain,
                lead_singles=self._chain_lead_singles(skip_steps),
            )
        else:
            units = ((1, b) for b in device_prefetch(host_batches, self.mesh))
        bar = self._progress_bar(num_batches, f"epoch {epoch + 1}")
        self._epoch_interrupted = False
        # Profiling capture (ProfileConfig): a no-op object reference when
        # off; when on, start/stop transitions fire at unit boundaries so
        # chained windows are traced whole — execution itself is untouched
        # (trace_counts + params bit-identical with capture off).
        cap = self._profile_capture
        watchdog = None
        # The watchdog pats once per executed unit; under chaining a window
        # legitimately takes ~chain step-times, so the timeout scales with it
        # (single-step fallback units then just run with extra slack).
        watchdog_timeout = self.step_timeout * chain if self.step_timeout else None
        self._watchdog_timeout = watchdog_timeout

        def sync_log_point():
            # Intra-epoch host syncs: this (every log_every steps — always a
            # window boundary, log_every % chain_steps == 0 is ctor-enforced)
            # and, multi-host only, the preemption vote (_preemption_requested).
            nonlocal synced_entries, synced_steps
            n_last, last = collected[-1]
            # Straggler sample FIRST (ISSUE 13): the float() fetches below
            # are about to block this host on every chip's window results —
            # sampling per-shard arrival order now observes WHICH chip the
            # sync is waiting on, at zero extra device syncs (the total
            # blocking time is the same either way).
            slow = None
            if self._straggler_on and self.fault_plan is not None:
                # Degraded-chip seam (ISSUE 16): a scheduled `slow_chip`
                # fault delays the named local device's shard arrival
                # inside the sample below — timing-only, numbers untouched.
                # Queried here (a sync point), NOT in the step loop: it
                # must never force chained windows into single-step mode.
                slow = self.fault_plan.slow_chip(
                    (d.id for d in jax.local_devices()), epoch=epoch
                )
                if slow is not None:
                    self.events.emit(
                        "fault_injection",
                        kind="slow_chip",
                        epoch=epoch,
                        step_in_epoch=step_in_epoch,
                        device=slow[0],
                        delay_ms=slow[1] * 1e3,
                    )
            strag = (
                straggler_lib.sample_arrivals(last, slow_chip=slow)
                if self._straggler_on
                else {}
            )
            m = {
                k: float(v[-1]) if n_last > 1 else float(v) for k, v in last.items()
            }
            if "nonfinite" in m:
                # The policy check must see every step since the last sync,
                # not just the latest — a guarded poison at step k<now has
                # nonfinite=1 only in ITS metrics. Chained windows report
                # per-step nonfinite flags (scan outputs), so the sum below
                # counts poisoned steps exactly as the single-step loop does.
                m_check = dict(m)
                m_check["nonfinite"] = float(
                    np.sum(
                        [
                            np.sum(np.asarray(x["nonfinite"]))
                            for _, x in collected[synced_entries:]
                        ]
                    )
                )
                synced_entries = len(collected)
                synced_steps = executed
                self._apply_nan_policy(m_check)
            else:
                self._apply_nan_policy(m)
            rate = executed * self.batch_size / (time.perf_counter() - t0)
            if bar is not None:
                bar.set_postfix(m, refresh=False)
                bar.clear()  # keep log lines off the live bar row
            self.log(f"  step {step_in_epoch}/{num_batches} {m} ({rate:.1f} img/s)")
            if bar is not None:
                bar.refresh()
            if self.telemetry is not None:
                # Per-window telemetry on the back of this host sync (the
                # float() fetches above) — step timing/MFU event, loss-scale
                # backoff detection, anomaly detectors. Zero extra syncs.
                now = time.perf_counter()
                window_steps = executed - tele_sync[1]
                window_s = now - tele_sync[0]
                tele_sync[0], tele_sync[1] = now, executed
                if window_steps > 0:
                    report = telemetry_mfu.window_report(
                        window_steps,
                        window_s,
                        flops_per_step=self._flops_per_step,
                        peak_flops=self._peak_flops,
                    )
                    self._last_step_ms = report["step_ms"]
                    mem_fields = self._live_memory_fields()
                    if strag:
                        # Normalize skew by this window's step wall — the
                        # floor-baselined anomaly signal and the doctor's
                        # attribution input.
                        strag["straggler_ratio"] = straggler_lib.ratio(
                            strag["chip_skew_ms"], report["step_ms"]
                        )
                        self._last_straggler = strag
                        if (
                            self._max_straggler_ratio is None
                            or strag["straggler_ratio"] > self._max_straggler_ratio
                        ):
                            self._max_straggler_ratio = strag["straggler_ratio"]
                    self.events.emit(
                        "window",
                        epoch=epoch,
                        step_in_epoch=step_in_epoch,
                        **report,
                        **mem_fields,
                        **strag,
                    )
                    # Liveness pulse + exporter snapshot (ISSUE 15): both
                    # ride this host sync — host counters already in hand,
                    # zero extra device syncs. The progress-field refresh
                    # is unconditional (patrol heartbeats must report the
                    # newest step even when the pulse itself debounces).
                    hb_fields = {
                        "epoch": epoch,
                        "step_in_epoch": step_in_epoch,
                        "units": getattr(self, "_attempt_units", 0) + executed,
                        "step_ms": report["step_ms"],
                    }
                    if mem_fields.get("live_bytes") is not None:
                        hb_fields["live_bytes"] = mem_fields["live_bytes"]
                    self._note_heartbeat_progress(**hb_fields)
                    self._emit_heartbeat("loop")
                    status_extra = dict(
                        step_in_epoch=step_in_epoch,
                        units=hb_fields["units"],
                        **mem_fields,
                    )
                    if strag.get("straggler_ratio") is not None:
                        status_extra["straggler_ratio"] = strag["straggler_ratio"]
                    if m.get("loss_scale") is not None:
                        status_extra["loss_scale"] = m["loss_scale"]
                    if m.get("loss") is not None:
                        status_extra["loss"] = m["loss"]
                    self._update_status(**status_extra)
                    scale = m.get("loss_scale")
                    if scale is not None:
                        if (
                            self._last_scale_seen is not None
                            and scale < self._last_scale_seen
                        ):
                            self.events.emit(
                                "loss_scale_backoff",
                                epoch=epoch,
                                step_in_epoch=step_in_epoch,
                                from_scale=self._last_scale_seen,
                                to_scale=scale,
                            )
                        self._last_scale_seen = scale
                    if self.anomaly_detector is not None:
                        now_traced = sum(self.engine.trace_counts.values())
                        window_compiled = now_traced > sync_trace[0]
                        sync_trace[0] = now_traced
                        self._report_anomalies(
                            self.anomaly_detector.observe(
                                step_in_epoch,
                                loss=m.get("loss", m.get("ce_loss")),
                                grad_norm=m.get("grad_norm"),
                                # None (absent) when this window paid
                                # compile: never fires, never feeds the
                                # baseline (see sync_trace above).
                                step_time=None
                                if window_compiled
                                else report["step_ms"] / 1e3,
                                live_bytes=mem_fields.get("live_bytes"),
                                straggler_ratio=strag.get("straggler_ratio"),
                            ),
                            epoch=epoch,
                            step_in_epoch=step_in_epoch,
                        )

        def tick_unit():
            # Attribute the just-executed unit's wall time: a unit whose
            # dispatch traced a new executable paid XLA compile (jit compiles
            # synchronously inside the call) — the compile bucket; every
            # cache-hit unit is productive step time.
            if self.telemetry is None:
                return
            traced = sum(self.engine.trace_counts.values()) - trace_base[0]
            if tm is not None:
                tm.tick("compile" if traced else "productive_step")
            if traced:
                if epoch > self._start_epoch:
                    # Compiles in the attempt's starting epoch (0 cold, the
                    # resume epoch after a restart) are warmup; a compile in
                    # the steady state is the retrace signature the doctor's
                    # compile_bound verdict keys on.
                    self._late_compiles += 1
                self.events.emit(
                    "compile",
                    epoch=epoch,
                    step_in_epoch=step_in_epoch,
                    executables=traced,
                )

        try:
            interrupted = False
            for n, batch in units:
                # First tick of the body: everything since the previous
                # unit's tick is the for statement's implicit next() — the
                # input pipeline wait.
                if tm is not None:
                    tm.tick("restart_rollback" if rollback_fetch else "data_wait")
                rollback_fetch = False
                if self.preflight is not None and not self._preflight_done:
                    # Before the first dispatch (nothing compiled yet): the
                    # unit's shapes are exact, the fit verdict covers the
                    # REAL program — the chained window when one can still
                    # occur this epoch (remaining steps >= chain_steps;
                    # an epoch shorter than one window only ever dispatches
                    # singles, and a verdict on the never-dispatched window
                    # program could fail a run whose real program fits).
                    # Predicted OOM raises out of the loop — failing fast
                    # host-side is the whole point. The abstract lowerings
                    # are one-time XLA compile work: booked to the `compile`
                    # bucket so goodput stays honest about the new startup
                    # cost.
                    self._run_memory_preflight(
                        n,
                        batch,
                        can_chain=chain > 1
                        and num_batches - step_in_epoch >= chain,
                    )
                    if tm is not None:
                        tm.tick("compile")
                if self.telemetry is not None:
                    trace_base[0] = sum(self.engine.trace_counts.values())
                if (
                    self._abstract_batch is None
                    and (self.telemetry is not None or cap is not None)
                ):
                    # Shapes only (ShapeDtypeStructs, no device ops): feeds
                    # the one-time MFU probe at epoch end and the profile
                    # capture's roofline join. A window leaf [n, B, ...]
                    # strips its leading step axis.
                    self._abstract_batch = jax.tree.map(
                        lambda x: jax.ShapeDtypeStruct(
                            x.shape if n == 1 else x.shape[1:], x.dtype
                        ),
                        batch,
                    )
                if n > 1 and not self._fault_active_in_window(
                    epoch, step_in_epoch, step_in_epoch + n
                ):
                    # -- chained window: one dispatch runs n steps on device.
                    # Preemption is polled at window boundaries only (the
                    # device program has no mid-window host hook), so saves
                    # land on boundaries and the watchdog/vote cadences above
                    # are scaled/rounded to match.
                    if self._preemption_requested(step_in_epoch):
                        self._preempted = True  # collective (multi-host OR)
                        interrupted = True
                        break
                    if cap is not None:
                        cap.maybe_start(step_in_epoch, self.state.params)
                    self.state, window_metrics = self.engine.train_steps_chained(
                        self.state, batch, n
                    )
                    collected.append((n, window_metrics))
                    step_in_epoch += n
                    executed += n
                    if cap is not None:
                        cap.maybe_stop(step_in_epoch, self.state.params)
                    watchdog = self._pat_watchdog(watchdog, watchdog_timeout)
                    if bar is not None:
                        bar.update(n)
                    if self.log_every and step_in_epoch % self.log_every == 0:
                        sync_log_point()
                    tick_unit()
                    continue
                # -- single-step path: lead/tail units, chain_steps == 1, and
                # windows with pending fault injections (unstacked so the
                # per-step injection points and preemption checks actually
                # run — semantics identical to the unchained loop).
                singles = (
                    (batch,)
                    if n == 1
                    else (self.engine.unstack_window(batch, i) for i in range(n))
                )
                for b in singles:
                    if self.fault_plan is not None:
                        b = self._inject_step_faults(b, epoch, step_in_epoch)
                    if self._preemption_requested(step_in_epoch):
                        self._preempted = True  # collective (multi-host OR)
                        interrupted = True
                        break
                    self._maybe_profile(step_in_epoch)
                    if cap is not None:
                        cap.maybe_start(step_in_epoch, self.state.params)
                    self.state, metrics = self.train_step(self.state, b)
                    collected.append((1, metrics))
                    step_in_epoch += 1
                    executed += 1
                    if cap is not None:
                        cap.maybe_stop(step_in_epoch, self.state.params)
                    watchdog = self._pat_watchdog(watchdog, watchdog_timeout)
                    if bar is not None:
                        # Advancing the bar is host-only; the postfix refreshes
                        # at the log_every sync points (a true per-step live
                        # loss would force the reference's per-step
                        # loss.item() sync back in).
                        bar.update(1)
                    if self.log_every and step_in_epoch % self.log_every == 0:
                        sync_log_point()
                tick_unit()
                if interrupted:
                    break
            if interrupted:
                self._epoch_interrupted = True
                self._interrupted_at_step = step_in_epoch
        except BaseException:
            # An abort with a capture window open (anomaly raise, watchdog
            # hung-step, nan_policy raise) must still stop the PROCESS-GLOBAL
            # jax.profiler session — leaving it running would make every
            # later start_trace in this process fail. sync=None: never block
            # teardown on (possibly hung) device work; abort=True: never pay
            # trace analysis or the roofline probe compile ahead of the
            # emergency-save path. The legacy profile_dir bracket holds the
            # same process-global session and needs the same teardown.
            if cap is not None and cap.state == "tracing":
                cap.maybe_stop(step_in_epoch, None, force=True, abort=True)
            if self._profiled == "tracing":
                try:
                    jax.profiler.stop_trace()
                except (OSError, RuntimeError):
                    pass  # teardown: the original exception must propagate
                self._profiled = True
            raise
        finally:
            if watchdog is not None:
                watchdog.stop()
        self._maybe_profile(step_in_epoch, end_of_epoch=True)
        if cap is not None:  # close a still-open capture window (short epoch)
            # A preemption-interrupted epoch is on the emergency-save clock:
            # abort=True skips trace analysis and the roofline probe compile
            # (same contract as the exception teardown above) — the grace
            # window is for the checkpoint, not a report.
            cap.maybe_stop(
                step_in_epoch,
                self.state.params,
                force=True,
                abort=self._epoch_interrupted,
            )
        if bar is not None:
            bar.close()
        if not collected:
            return {}
        # ONE host transfer for the whole epoch, then expand window records
        # to per-step dicts host-side (free: numpy indexing, no device ops).
        host: list[dict] = []
        for k, tree in jax.device_get(collected):
            if k == 1:
                host.append(tree)
            else:
                host.extend(
                    {key: v[i] for key, v in tree.items()} for i in range(k)
                )
        if tm is not None:
            # The device_get above drained every in-flight step — that wait
            # is device execution, i.e. productive time.
            tm.tick("productive_step")
        # Epoch wall time is closed BEFORE the MFU probe: the probe's one-time
        # XLA compile (seconds to minutes on a real model) must not inflate
        # this epoch's step_ms/MFU report — a first-epoch step-time figure
        # 2.5x the window baseline would fire a spurious step_time_regression.
        epoch_wall = time.perf_counter() - t0
        self._maybe_probe_mfu()  # one-time; attributes itself to `compile`
        out = self._aggregate_epoch_metrics(host, synced_steps)
        if self.telemetry is not None and executed:
            report = telemetry_mfu.window_report(
                executed,
                epoch_wall,
                flops_per_step=self._flops_per_step,
                peak_flops=self._peak_flops,
            )
            self._last_step_ms = report["step_ms"]
            health = {
                k: out[k]
                for k in ("loss", "ce_loss", "grad_norm", "update_ratio", "nonfinite")
                if k in out
            }
            mem_fields = self._live_memory_fields()
            epoch_fields = {}
            if self.goodput is not None:
                # Cumulative goodput snapshot per epoch: the timeline
                # exporter turns consecutive snapshots into per-bucket
                # spans, and the offline doctor reads the last one.
                epoch_fields["goodput_seconds"] = self.goodput.to_state()
            if self._last_straggler:
                epoch_fields["chip_skew_ms"] = self._last_straggler["chip_skew_ms"]
                epoch_fields["straggler_ratio"] = self._last_straggler[
                    "straggler_ratio"
                ]
            self.events.emit(
                "epoch_end",
                epoch=epoch,
                wall_s=epoch_wall,
                interrupted=self._epoch_interrupted,
                **report,
                **health,
                **mem_fields,
                **epoch_fields,
            )
            self._attempt_units = getattr(self, "_attempt_units", 0) + executed
            self._note_heartbeat_progress(
                epoch=epoch, step_in_epoch=step_in_epoch,
                units=self._attempt_units, step_ms=report["step_ms"],
            )
            self._emit_heartbeat("loop")
            self._update_status(
                step_in_epoch=step_in_epoch, units=self._attempt_units,
                **mem_fields,
            )
            if self.anomaly_detector is not None:
                epoch_compiled = (
                    sum(self.engine.trace_counts.values()) > epoch_trace_start
                )
                self._report_anomalies(
                    self.anomaly_detector.observe(
                        step_in_epoch,
                        loss=out.get("loss", out.get("ce_loss")),
                        grad_norm=out.get("grad_norm"),
                        # An epoch that paid compile (epoch 0, or a resume
                        # retrace) reports a compile-diluted mean step
                        # time: withheld, like the per-window rule above.
                        step_time=None
                        if epoch_compiled
                        else report["step_ms"] / 1e3,
                        live_bytes=mem_fields.get("live_bytes"),
                    ),
                    epoch=epoch,
                    step_in_epoch=step_in_epoch,
                )
        return out

    def _aggregate_epoch_metrics(self, host: list[dict], synced: int = 0) -> dict:
        """Per-epoch means. Under the non-finite guard, poisoned steps are
        excluded from the means (their loss is NaN by construction — averaging
        it in would report a NaN epoch even though training recovered) and
        ``nonfinite`` reports the skipped-step COUNT instead. The policy check
        covers only steps after the last intra-epoch sync (``synced``) — a
        poison already handled at a log_every sync must not re-trigger."""
        if "nonfinite" not in host[0]:
            out = {k: float(np.mean([m[k] for m in host])) for k in host[0]}
            self._apply_nan_policy(out)
            return out
        bad = int(np.sum([m["nonfinite"] for m in host]))
        self.nonfinite_steps += bad
        good = [m for m in host if not m["nonfinite"]]
        out = {
            k: float(np.mean([m[k] for m in good])) if good else float("nan")
            for k in host[0]
            if k != "nonfinite"
        }
        out["nonfinite"] = float(bad)
        check = dict(out)
        check["nonfinite"] = float(np.sum([m["nonfinite"] for m in host[synced:]]))
        self._apply_nan_policy(check)
        return out

    def _apply_nan_policy(self, host_metrics: dict) -> None:
        """Run at host sync points only (log_every / epoch end) — detection
        adds zero extra device syncs. ``host_metrics`` values are floats."""
        if self.nan_policy is None:
            return
        poisoned = host_metrics.get("nonfinite", 0.0) > 0 or any(
            not np.isfinite(v) for v in host_metrics.values()
        )
        if not poisoned:
            return
        if self.nan_policy == "raise":
            raise NonFiniteLossError(
                f"non-finite training metrics: {host_metrics} "
                "(nan_policy='raise'; use 'skip' or 'restore_last_good' to "
                "degrade gracefully)"
            )
        if self.nan_policy == "restore_last_good":
            # Serialize with the background committer: the rollback must see
            # a fully committed newest checkpoint (and the manager is
            # single-threaded by contract — see AsyncCheckpointSaver).
            self._flush_saver_logged()
            try:
                self.state, epoch, name = self.checkpoints.restore_latest_valid(
                    self.state
                )
            except CheckpointError:
                # Nothing saved yet (NaN before the first checkpoint): the
                # engine guard already dropped the poisoned update, so
                # degrading to skip-semantics is safe — and still graceful.
                self.log(
                    "non-finite step detected but no valid checkpoint exists "
                    "yet — update was skipped, training continues",
                    "warning",
                )
                return
            self.nonfinite_rollbacks += 1
            self.log(
                f"non-finite step detected — rolled state back to checkpoint "
                f"{name!r} (epoch {epoch})",
                "warning",
            )

    def _inject_step_faults(self, batch, epoch: int, step: int):
        """Deterministic fault-injection points (fault/inject.py): a real
        SIGTERM, a simulated hung step, or a NaN-poisoned batch. Every
        firing lands in the telemetry event log (rank-0, no-op when off) so
        a test run's flight record shows exactly which faults fired where."""
        fired_before = len(self.fault_plan.fired)
        self.fault_plan.maybe_sigterm(epoch=epoch, step=step)
        hang = self.fault_plan.fires("hang", epoch=epoch, step=step)
        if hang is not None:
            time.sleep(float(hang.payload or 0.0))
        if self.fault_plan.fires("nan_loss", epoch=epoch, step=step) is not None:
            batch = jax.tree.map(
                lambda x: jnp.full_like(x, jnp.nan)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                batch,
            )
        if self.events.enabled:
            for kind, ctx in self.fault_plan.fired[fired_before:]:
                self.events.emit("fault_injection", kind=kind, **ctx)
        return batch

    _hung_once = False

    def _on_hung_step(self) -> None:
        # Watchdog-thread callback. First fire: reuse the preemption
        # machinery (SIGTERM -> flag -> collective save at the next safe
        # point) — recovers steps that are slow but eventually return.
        # Second fire: the main thread is truly wedged (blocked inside a
        # collective or I/O call that will never return to the loop's
        # preemption check), so a graceful save is impossible — hard-exit
        # with EX_TEMPFAIL so the scheduler restarts from the last
        # checkpoint. That IS the bounded loss; the alternative is a silent
        # stall until the job-level timeout.
        timeout = self._watchdog_timeout or self.step_timeout
        if self._hung_once:
            self.log(
                f"watchdog: still no progress {timeout}s after "
                "SIGTERM — main thread is wedged; hard-exiting for scheduler "
                "restart (resume from the last checkpoint)",
                "error",
            )
            os._exit(75)  # EX_TEMPFAIL
        self._hung_once = True
        self._hung_steps += 1
        self.log(
            f"watchdog: no step completed in {timeout}s — forcing a "
            "preemption-style resumable save",
            "warning",
        )
        self.events.emit("hung_step", timeout_s=timeout)
        os.kill(os.getpid(), signal.SIGTERM)

    def _on_preemption_signal(self, signum, frame) -> None:
        # Flag only — saves are collective and cannot run in signal context.
        self._preempted = True
        # Chain to whatever handler was installed before this trainer, so a
        # Trainer never swallows someone else's SIGTERM semantics.
        if callable(self._prev_sigterm):
            self._prev_sigterm(signum, frame)

    def _install_sigterm(self) -> None:
        if not self.save_on_preemption or self._sigterm_installed:
            return
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, self._on_preemption_signal)
            self._sigterm_installed = True
        except ValueError:
            pass  # not the main thread (e.g. trainer driven from a worker)

    def _restore_sigterm(self) -> None:
        if self._sigterm_installed:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm or signal.SIG_DFL)
            except ValueError:
                pass
            self._sigterm_installed = False

    def _preemption_requested(self, step_in_epoch: int) -> bool:
        """Collective preemption decision. Per-host SIGTERM delivery is not
        synchronized; if each host acted on its local flag alone, hosts could
        break on different steps — one skipping a collective its peers entered
        (deadlock inside the eviction grace window). All hosts therefore agree
        on the OR of their flags at the same loop points, every
        ``preemption_check_every`` steps — a bounded reaction latency
        independent of ``log_every`` (an ImageNet epoch is far longer than an
        eviction grace window, so epoch-boundary-only checking is not enough).
        Single-process polls its local flag every step for free."""
        if jax.process_count() == 1:
            return self._preempted
        cadence = self.preemption_check_every
        if not cadence or step_in_epoch % cadence != 0:
            return False
        return self._collective_preempt_flag()

    def _collective_preempt_flag(self) -> bool:
        """OR of every host's local flag — identical answer on all hosts.
        Must be called at the same program points on every host."""
        if jax.process_count() == 1:
            return self._preempted
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._preempted], dtype=np.bool_)
        )
        return bool(np.any(flags))

    def _progress_bar(self, total: int, desc: str):
        """Live per-step progress display (reference shows a tqdm bar with live
        postfix metrics, ``trainer/trainer.py:143,148``). Process 0 only."""
        if not self.progress or jax.process_index() != 0:
            return None
        try:
            from tqdm import tqdm
        except ImportError:
            return None
        return tqdm(total=total, desc=desc, dynamic_ncols=True, leave=False)

    def _maybe_profile(self, step_in_epoch: int, end_of_epoch: bool = False) -> None:
        """Trace steps [1, 1+profile_steps) of the first trained epoch —
        step 0 is excluded so compile time never pollutes the trace."""
        if self.profile_dir is None or self._profiled is True:
            return
        if self._profiled == "tracing" and (
            end_of_epoch or step_in_epoch >= 1 + self.profile_steps
        ):
            jax.block_until_ready(self.state.params)
            jax.profiler.stop_trace()
            self._profiled = True
            self.log(f"Profiler trace written to {self.profile_dir}")
        elif self._profiled is False and not end_of_epoch and step_in_epoch == 1:
            jax.block_until_ready(self.state.params)
            os.makedirs(self.profile_dir, exist_ok=True)
            jax.profiler.start_trace(self.profile_dir)
            self._profiled = "tracing"

    def validate(self) -> dict:
        """Collective validation over the val loader; returns weighted-mean
        metrics (pad-mask aware). Twin of ``trainer/trainer.py:184-206``."""
        sums: dict[str, Any] = {}
        weight_total = 0.0
        mask_contract_checked = False
        for b, host_batch in enumerate(self.val_dataloader):
            host_batch = self.preprocess_batch(host_batch)
            # Weight by the batch's GLOBAL real-row count — identical on every
            # process (a host-local mask sum would diverge across hosts on the
            # padded final batch and break collective best-checkpoint decisions).
            if hasattr(self.val_dataloader, "global_real_count"):
                weight = float(self.val_dataloader.global_real_count(b))
            else:
                weight = float(len(next(iter(host_batch.values()))))
            # Contract check (once, on the first batch that actually contains
            # padding — global real count below the global batch size):
            # real-count weighting is only exact when the user's metrics
            # down-weight padded rows via batch["mask"] (ops.weighted_mean).
            # A criterion that ignores the mask gets pad-diluted values
            # silently combined with real-row weights.
            if (
                not mask_contract_checked
                and "mask" in host_batch
                and np.asarray(host_batch["mask"]).min() == 0  # padding present
            ):
                mask_contract_checked = True
                if getattr(self, "criterion_uses_mask", None) is not True:
                    self.log(
                        "this validation batch is padded (batch['mask']): "
                        "metrics must down-weight padded rows (ops.weighted_mean) "
                        "or they are diluted. Set self.criterion_uses_mask = True "
                        "once your build_criterion handles the mask to silence "
                        "this.",
                        "warning",
                    )
            batch = self.engine.shard_batch(host_batch)
            metrics = self.validate_step(self.state, batch)
            # Weighted sums accumulate as device scalars; the epoch's single
            # host sync is the device_get below (the reference syncs per batch
            # via .item(), ``example_trainer.py:101-102``).
            for k, v in dict(metrics).items():
                sums[k] = sums.get(k, 0.0) + v * weight
            weight_total += weight
        sums = jax.device_get(sums)
        avg = {k: float(v) / max(weight_total, 1.0) for k, v in sums.items()}
        msg = "VALIDATE RESULTS: "
        for k, v in avg.items():
            msg += f" | {k} = {v} | "
        self.log(msg)
        self.metrics_writer.write(int(self.state.step), avg, prefix="val")
        return avg

    # ------------------------------------------------------------------
    # The nine hooks (``trainer/trainer.py:219-253``) — same names.
    # ------------------------------------------------------------------

    def build_train_dataset(self):
        raise NotImplementedError("Please implement the build_train_dataset method")

    def build_val_dataset(self):
        raise NotImplementedError("Please implement the build_val_dataset method")

    def build_model(self):
        raise NotImplementedError("Please implement the build_model method")

    def build_criterion(self):
        raise NotImplementedError("Please implement the build_criterion method")

    def build_optimizer(self, schedule: optax.Schedule):
        raise NotImplementedError("Please implement the build_optimizer method")

    def build_scheduler(self):
        raise NotImplementedError("Please implement the build_scheduler method")

    def build_sharding_rules(self):
        """Advanced hook (the ``build_loss_fn`` convention): the explicit
        ``(path_regex, PartitionSpec)`` parameter-sharding rules handed to
        the engine when the ctor's ``sharding_rules="auto"`` (the default).
        The default is ``parallel.default_sharding_rules(mesh)`` — the one
        resolution policy shared with bench.py's BENCH_MESH setup, so the
        bench measures the same program the Trainer runs: a mesh with a
        nontrivial ``tensor`` axis gets ``transformer_tp_rules()``
        (Megatron-style TP for the ViT/LM transformer blocks — conv models
        match none of its patterns and fall through to the FSDP/replicated
        fallback), any other mesh gets None (pure FSDP via ``spec_for_leaf``
        / ``_fsdp_spec``, or fully replicated on a pure-data mesh — the
        historical program). Override to hand-place specs for a custom
        model."""
        from distributed_training_pytorch_tpu.parallel import (
            default_sharding_rules,
        )

        return default_sharding_rules(self.mesh)

    def build_loss_fn(self):
        """Advanced hook (beyond the reference's nine): the full functional
        LossFn handed to the engine. The default composes ``build_model`` +
        ``build_criterion`` the standard way; override when the loss needs
        direct access to params (e.g. ``ops.losses.tied_cross_entropy`` fusing
        a tied LM head so the [B, T, V] logits never materialize)."""
        return make_supervised_loss(self.model, self.criterion)

    def preprocess_batch(self, batch: Mapping) -> Mapping:
        """Host-side batch hook. The reference uses this for the H2D copy
        (``example_trainer.py:68-70``); here transfer is the framework's job,
        so the default is identity."""
        return batch

    _image_range_checked = False

    def _check_image_range(self, batch: Mapping) -> Mapping:
        """One-time foot-gun guard (first train batch only): a FLOAT image
        batch whose values span raw-pixel range almost certainly missed its
        normalize — ``models.InputNormalizer`` passes floats through as
        already normalized, so the model would train on ~100x-misscaled
        input with no error anywhere else."""
        if not self._image_range_checked:
            self._image_range_checked = True
            img = batch.get("image") if hasattr(batch, "get") else None
            if img is not None and np.issubdtype(np.asarray(img).dtype, np.floating):
                hi = float(np.max(np.abs(np.asarray(img[:1]))))
                if hi > 16.0:  # normalized images sit within a few sigma of 0
                    self.log(
                        f"float image batch spans |x| up to {hi:.0f} — looks like "
                        "raw 0-255 pixels. Float inputs bypass on-device "
                        "normalization (InputNormalizer passes them through); "
                        "ship uint8 or normalize on host.",
                        "warning",
                    )
        return batch

    def train_step(self, state, batch):
        """Default: the engine's compiled grad/reduce/update step."""
        return self.engine.train_step(state, batch)

    def validate_step(self, state, batch):
        """Default: the engine's compiled collective eval step."""
        return self.engine.eval_step(state, batch)

    # ------------------------------------------------------------------
    # Lifecycle statics — ``ddp_setup``/``destroy_process`` twins (``:74-82``).
    # ------------------------------------------------------------------

    @staticmethod
    def distributed_setup(**kwargs) -> None:
        mesh_lib.setup_distributed(**kwargs)

    @staticmethod
    def destroy_process() -> None:
        mesh_lib.shutdown_distributed()
