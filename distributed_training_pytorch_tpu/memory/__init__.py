"""Memory observability subsystem (ISSUE 8): where did the HBM go.

The time-side observability stack (telemetry goodput, StepProfile device
attribution, MFU) answers "where did the wall clock go"; this package is
its memory twin — three layers over one sizing convention
(``utils.hlo_flops.aval_bytes`` / ``DTYPE_BYTES``):

* :mod:`~.analysis`  — per-buffer attribution of the compiled program's
  predicted peak (``compiled.memory_analysis()`` off the abstract-aval
  probe: zero device execution, CPU-viable) into params / optimizer state /
  gradients / activations / input batch / executable, fractions summing to
  1 by construction, plus a largest-buffers table;
* :mod:`~.preflight` — fit prediction *before* the first dispatch, with a
  bisection over abstract lowerings recommending the max batch and/or
  microbatch factor that fits (``Trainer(preflight=...)``; ``None``
  reproduces the historical program exactly);
* :mod:`~.live`      — the ONE ``device.memory_stats()`` read shared by
  bench, trainer telemetry, and preflight (live/peak bytes, per-chip skew,
  the peak-is-process-lifetime caveat), degrading to absent fields on
  statless backends.

Wire-up: ``Trainer(preflight="on")``; window events carry ``live_bytes``;
``telemetry.anomaly`` grows a ``memory_growth`` leak detector; see
``docs/memory.md``. CI gate: ``scripts/memory_probe.py``.
"""

from distributed_training_pytorch_tpu.memory.analysis import (  # noqa: F401
    BUFFER_CLASSES,
    MemoryProfile,
    analyze_step_memory,
    attribute_memory,
    memory_stats_dict,
    predicted_peak_bytes,
    top_buffers_from_hlo,
)
from distributed_training_pytorch_tpu.memory.live import (  # noqa: F401
    device_capacity_bytes,
    device_memory_stats,
    is_oom_error,
    live_memory_fields,
    memory_skew,
    window_memory_fields,
)
from distributed_training_pytorch_tpu.memory.preflight import (  # noqa: F401
    Preflight,
    PreflightOOMError,
    PreflightReport,
    resolve_preflight,
    run_preflight,
)

__all__ = [
    "BUFFER_CLASSES",
    "MemoryProfile",
    "Preflight",
    "PreflightOOMError",
    "PreflightReport",
    "analyze_step_memory",
    "attribute_memory",
    "device_capacity_bytes",
    "device_memory_stats",
    "is_oom_error",
    "live_memory_fields",
    "memory_skew",
    "memory_stats_dict",
    "predicted_peak_bytes",
    "resolve_preflight",
    "run_preflight",
    "top_buffers_from_hlo",
    "window_memory_fields",
]
