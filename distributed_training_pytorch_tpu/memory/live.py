"""Live device-memory telemetry: the ONE ``memory_stats`` read.

Three consumers watch allocator state — ``bench.py``'s per-sweep-entry
memory fields, the trainer's per-window telemetry, and the preflight
layer's capacity lookup — and before this module each grew its own inline
read with its own caveat comments. One implementation, one contract:

* ``device.memory_stats()`` is a host-side PJRT allocator query — **no
  device sync** — so reading it at the trainer's existing ``log_every``
  sync points adds zero host syncs to the hot loop;
* backends without allocator stats (CPU, some plugin paths) return
  ``None``/raise; every helper here **degrades to absent fields** rather
  than fabricating numbers (the events/bench consumers simply omit the
  keys — test-enforced);
* ``peak_bytes`` is the allocator's **process-lifetime high-water mark**
  with no reset: in a sweep, only the first run's peak describes that run —
  later (smaller) configs would silently report the earlier run's peak,
  which is why ``live_memory_fields(include_peak=False)`` exists and why
  the trainer's window records keep ``live_bytes`` as the per-window
  signal (the growth detector watches it, not the peak).
"""

from __future__ import annotations

import jax

__all__ = [
    "device_capacity_bytes",
    "device_memory_stats",
    "is_oom_error",
    "live_memory_fields",
    "memory_skew",
    "window_memory_fields",
]


def device_memory_stats(device=None) -> dict | None:
    """``device.memory_stats()`` or None when the backend has none (CPU) —
    the single implementation of the read every consumer shares."""
    if device is None:
        device = jax.local_devices()[0]
    try:
        stats = device.memory_stats()
    except (AttributeError, NotImplementedError, RuntimeError):
        return None
    return dict(stats) if stats else None


def device_capacity_bytes(device=None) -> int | None:
    """Per-device memory capacity (``bytes_limit`` — the allocator's HBM
    budget), or None when the backend reports no stats. The preflight
    layer's denominator."""
    stats = device_memory_stats(device)
    if not stats:
        return None
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    return int(limit) if limit else None


def live_memory_fields(device=None, *, include_peak: bool = True) -> dict:
    """``{"live_bytes": ..., "peak_bytes": ...}`` from the allocator, or
    ``{}`` on statless backends. ``include_peak=False`` drops the
    process-lifetime high-water mark (see module docstring) — sweep runs
    after the first must not report the first run's peak as theirs."""
    stats = device_memory_stats(device)
    if not stats:
        return {}
    out = {}
    if "bytes_in_use" in stats:
        out["live_bytes"] = int(stats["bytes_in_use"])
    if include_peak and "peak_bytes_in_use" in stats:
        out["peak_bytes"] = int(stats["peak_bytes_in_use"])
    return out


def window_memory_fields(devices=None, *, include_peak: bool = True) -> dict:
    """The trainer's per-window record: ONE pass over the local devices
    producing device 0's ``live_bytes``/``peak_bytes`` AND the multi-chip
    ``live_bytes_min/max/skew`` from the same sampling instant — two
    separate reads could land allocations between them and emit a
    self-contradictory record (``live_bytes`` outside its own min/max).
    ``{}`` on statless backends."""
    if devices is None:
        devices = jax.local_devices()
    per_device = [device_memory_stats(d) for d in devices]
    out = {}
    first = per_device[0] if per_device else None
    if first:
        if "bytes_in_use" in first:
            out["live_bytes"] = int(first["bytes_in_use"])
        if include_peak and "peak_bytes_in_use" in first:
            out["peak_bytes"] = int(first["peak_bytes_in_use"])
    if len(per_device) >= 2 and all(
        s and "bytes_in_use" in s for s in per_device
    ):
        live = [int(s["bytes_in_use"]) for s in per_device]
        out["live_bytes_min"] = min(live)
        out["live_bytes_max"] = max(live)
        out["live_bytes_skew"] = max(live) - min(live)
    return out


def memory_skew(devices=None) -> dict:
    """Per-chip live-byte skew on multi-chip hosts: ``{"live_bytes_min",
    "live_bytes_max", "live_bytes_skew"}`` (max - min). A data-parallel
    step's live set should be near-identical per chip; persistent skew
    means one chip carries buffers its peers do not (a leaked per-device
    array, an unsharded constant) and will OOM first. ``{}`` on single-chip
    hosts or statless backends. A thin filter over
    :func:`window_memory_fields` — ONE implementation of the multi-device
    pass, one sampling instant."""
    return {
        k: v
        for k, v in window_memory_fields(devices, include_peak=False).items()
        if k.startswith("live_bytes_")
    }


def is_oom_error(err: BaseException) -> bool:
    """Whether an exception is a DEVICE out-of-memory: XLA surfaces
    allocator exhaustion as ``XlaRuntimeError("RESOURCE_EXHAUSTED: ...")``
    (the bench sweep's per-entry net catches exactly this and emits a
    structured ``{"oom": true}`` line instead of killing the sweep).
    Host-side ``MemoryError`` is deliberately NOT classified: the net must
    report fit boundaries the device actually hit — host-RAM exhaustion
    wearing the same name is a bug to surface, not a boundary to record."""
    text = str(err)
    if "RESOURCE_EXHAUSTED" in text:
        return True
    return type(err).__name__ == "XlaRuntimeError" and "out of memory" in text.lower()
