"""Per-buffer HBM attribution from the compiled program — "where did the
memory go", the peer of the profiling subsystem's "where did the time go".

Every memory limit in this repo's history was discovered by crashing into
it (the GPT-2 LM OOM past batch 32, the ViT 384 MB/layer pallas OOM, the
MoE einsum OOM at 65k tokens, the bench microbatch split added because
B=4096 "OOMs on one chip"). The instrument that prevents the next one reads
XLA's own buffer assignment — ``compiled.memory_analysis()`` — off the
*real* single-step and chained programs, lowered on abstract avals via the
existing ``TrainEngine.compile_step_probe`` machinery: zero device
execution, CPU-viable like the HLO audit, dispatch executables and
``trace_counts`` untouched.

The attribution convention is the PR-6 ``StepProfile`` one: **fractions sum
to 1 by construction**. XLA reports four byte totals (arguments, outputs,
aliased outputs, temps) plus generated code; those are partitioned into the
six buffer classes of :data:`BUFFER_CLASSES`:

* ``params`` / ``optimizer_state`` / ``input_batch`` — the argument total,
  pro-rated over the aval byte sizes (``utils.hlo_flops.aval_bytes``) of the
  corresponding input leaves. Pro-rata against XLA's *reported* argument
  bytes (rather than trusting the aval sum) keeps the partition exact when
  the backend pads/aligns buffers;
* ``gradients`` — the slice of the temp total up to the params' aval bytes
  (the grad tree mirrors the master params; XLA may alias grads away, in
  which case the class shrinks to what temp space actually exists);
* ``activations`` — the remaining temps plus unaliased outputs. For the
  dispatch program (donate mirrored, 100% param/opt-state aliasing enforced
  by the static audit) unaliased outputs are just the metrics; for an
  undonated probe the fresh output state lands here too — extra live memory
  at peak is extra live memory, whatever its name;
* ``executable`` — XLA's generated-code size (the program itself lives in
  device memory on TPU).

``peak_bytes = arguments + outputs - aliased + temps + code`` is the
standard fit predictor for an XLA executable; the preflight layer
(``memory.preflight``) compares it against device capacity *before* the
first dispatch.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Mapping, Sequence

import jax

# The one chain-window stacking rule lives on the engine now, shared with
# the HLO and comm audits (explicit re-export: existing importers of
# memory.analysis keep working).
from distributed_training_pytorch_tpu.train.engine import (
    stack_chain_batch as stack_chain_batch,
)
from distributed_training_pytorch_tpu.utils.hlo_flops import DTYPE_BYTES, aval_bytes

__all__ = [
    "BUFFER_CLASSES",
    "MemoryProfile",
    "analyze_step_memory",
    "attribute_memory",
    "batch_class_bytes",
    "memory_stats_dict",
    "predicted_peak_bytes",
    "state_class_bytes",
    "top_buffers_from_hlo",
]

# The exhaustive buffer-class partition, in reporting order.
BUFFER_CLASSES = (
    "params",
    "optimizer_state",
    "gradients",
    "activations",
    "input_batch",
    "executable",
)

# CompiledMemoryStats attributes consumed below (device-side set only; the
# host_* twins describe host-offloaded buffers this framework never emits).
_STAT_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
    "generated_code_size_in_bytes",
)


def memory_stats_dict(compiled) -> dict | None:
    """``compiled.memory_analysis()`` flattened to a plain int dict (the
    :data:`_STAT_FIELDS` subset), or None when the backend reports none —
    the universal degrade-to-absent contract of ``device.memory_stats``."""
    analysis = getattr(compiled, "memory_analysis", None)
    if analysis is None:
        return None
    try:
        stats = analysis()
    except (NotImplementedError, RuntimeError):
        return None
    if stats is None:
        return None
    return {field: int(getattr(stats, field)) for field in _STAT_FIELDS}


def predicted_peak_bytes(compiled) -> int | None:
    """Predicted peak device bytes of one dispatch of ``compiled``:
    ``arguments + outputs - aliased + temps + generated code``. None when
    the backend exposes no memory analysis."""
    stats = memory_stats_dict(compiled)
    if stats is None:
        return None
    return _peak_from_stats(stats)


def _peak_from_stats(stats: Mapping[str, int]) -> int:
    return int(
        stats["argument_size_in_bytes"]
        + stats["output_size_in_bytes"]
        - stats["alias_size_in_bytes"]
        + stats["temp_size_in_bytes"]
        + stats["generated_code_size_in_bytes"]
    )


def _tree_bytes(tree, shardings=None) -> float:
    """Byte total of a pytree's leaves. ``shardings`` (a single Sharding or
    a matching tree; see ``parallel.sharding.tree_shard_bytes``) sizes each
    leaf at its PER-DEVICE shard shape — the convention an SPMD program's
    ``memory_analysis()`` reports in (measured: a data-sharded batch's
    argument bytes are batch/extent, an fsdp-sharded param's are
    param/extent). None = global aval bytes (replicated)."""
    if shardings is not None:
        from distributed_training_pytorch_tpu.parallel.sharding import (
            tree_shard_bytes,
        )

        return tree_shard_bytes(tree, shardings)
    return float(
        sum(
            aval_bytes(tuple(leaf.shape), getattr(leaf, "dtype", None))
            for leaf in jax.tree.leaves(tree)
        )
    )


def state_class_bytes(state, shardings=None) -> dict[str, float]:
    """Byte totals of a ``TrainState``'s leaves by buffer class: ``params``
    (master params + model collections like BN stats) and
    ``optimizer_state`` (optax state, plus the step/rng/loss-scale
    bookkeeping leaves — a few dozen bytes riding the bigger class).
    ``shardings`` (a ``TrainState``-shaped tree of ``NamedSharding``s, e.g.
    ``TrainEngine.state_sharding_tree``) switches every leaf to PER-DEVICE
    shard bytes — required for sharded programs, where the global aval sum
    would overstate an fsdp/tensor-sharded class by its shard extent."""
    sh = shardings
    params = _tree_bytes(
        getattr(state, "params", None), getattr(sh, "params", None)
    ) + _tree_bytes(
        getattr(state, "model_state", None), getattr(sh, "model_state", None)
    )
    optimizer = (
        _tree_bytes(getattr(state, "opt_state", None), getattr(sh, "opt_state", None))
        + _tree_bytes(getattr(state, "step", None), getattr(sh, "step", None))
        + _tree_bytes(getattr(state, "rng", None), getattr(sh, "rng", None))
        + _tree_bytes(
            getattr(state, "loss_scale", None), getattr(sh, "loss_scale", None)
        )
    )
    return {"params": params, "optimizer_state": optimizer}


def batch_class_bytes(batch, sharding=None) -> float:
    """Byte total of the input batch tree (for a chained program, the whole
    chain-stacked window — ``chain_steps`` global batches are live in device
    memory at once, which is exactly why chained windows move the fit
    boundary). ``sharding`` (the engine's batch / chain-batch
    ``NamedSharding``) sizes the PER-DEVICE shard: the batch dim splits over
    data x fsdp, so each device stages only its own rows."""
    return _tree_bytes(batch, sharding)


# One optimized-HLO definition line: `%name = dtype[dims]{layout} opcode(`.
# Tuple-shaped defs (while carries, fusion roots) deliberately don't match —
# their bytes are the element buffers', each defined on its own line.
_BUF_RE = re.compile(
    r"^(?:ROOT )?%([\w.\-]+) = (\w+)\[([0-9,]*)\](?:\{[^}]*\})? ([\w\-]+)\("
)
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def top_buffers_from_hlo(hlo_text: str, top_k: int = 10) -> list[dict]:
    """The ``top_k`` largest buffers of an optimized-HLO module: one row per
    instruction output — ``{name, op, shape, dtype, bytes, op_name}`` —
    sized with the same dtype-width table ``aval_bytes`` uses
    (``utils.hlo_flops.DTYPE_BYTES``), so the largest-buffers table and the
    class attribution account memory identically. ``op_name`` is the origin
    op from HLO metadata when present (the model-level name of the op that
    produced the buffer)."""
    if top_k <= 0:
        return []
    rows: list[dict] = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = _BUF_RE.match(line)
        if not m:
            continue
        name, dtype, dims_s, op = m.groups()
        dims = tuple(int(x) for x in dims_s.split(",") if x)
        n = 1
        for d in dims:
            n *= d
        origin = _OPNAME_RE.search(line)
        rows.append(
            {
                "name": name,
                "op": op,
                "shape": list(dims),
                "dtype": dtype,
                "bytes": int(n * DTYPE_BYTES.get(dtype, 4)),
                "op_name": origin.group(1) if origin else "",
            }
        )
    rows.sort(key=lambda r: r["bytes"], reverse=True)
    return rows[:top_k]


@dataclasses.dataclass
class MemoryProfile:
    """Peak-HBM attribution of one compiled step (or chained window).

    ``bytes_by_class`` partitions ``peak_bytes`` over :data:`BUFFER_CLASSES`
    exactly (sum == peak by construction, so :meth:`fractions` sum to 1);
    ``stats`` carries the raw ``CompiledMemoryStats`` totals the partition
    was derived from; ``top_buffers`` the largest-buffer rows."""

    peak_bytes: int
    bytes_by_class: dict[str, float]
    stats: dict[str, int]
    top_buffers: list[dict] = dataclasses.field(default_factory=list)
    chain_length: int | None = None

    def fractions(self) -> dict[str, float]:
        if self.peak_bytes <= 0:
            return {c: 0.0 for c in BUFFER_CLASSES}
        return {c: v / self.peak_bytes for c, v in self.bytes_by_class.items()}

    def to_fields(self) -> dict:
        """Flat JSON-safe payload for events / bench lines."""
        return {
            "predicted_peak_bytes": int(self.peak_bytes),
            "bytes_by_class": {k: int(v) for k, v in self.bytes_by_class.items()},
            "fractions": {k: round(v, 4) for k, v in self.fractions().items()},
            **({"chain_length": self.chain_length} if self.chain_length else {}),
        }


def attribute_memory(
    stats: Mapping[str, int],
    input_class_bytes: Mapping[str, float],
    grad_bytes: float,
    *,
    top_buffers: Sequence[dict] = (),
    chain_length: int | None = None,
) -> MemoryProfile:
    """Pure-arithmetic attribution core (hand-testable without XLA).

    ``stats`` is a :func:`memory_stats_dict`; ``input_class_bytes`` the aval
    byte totals of the argument leaves per class (``params`` /
    ``optimizer_state`` / ``input_batch``); ``grad_bytes`` the params' aval
    bytes (the gradient tree's size). See the module docstring for the
    partition rules."""
    arg = float(stats["argument_size_in_bytes"])
    out = float(stats["output_size_in_bytes"])
    alias = float(stats["alias_size_in_bytes"])
    temp = float(stats["temp_size_in_bytes"])
    code = float(stats["generated_code_size_in_bytes"])

    classes = {c: 0.0 for c in BUFFER_CLASSES}
    in_total = sum(input_class_bytes.get(c, 0.0) for c in ("params", "optimizer_state", "input_batch"))
    if in_total > 0:
        for c in ("params", "optimizer_state", "input_batch"):
            classes[c] = arg * (input_class_bytes.get(c, 0.0) / in_total)
        spill = 0.0
    else:
        spill = arg  # no classable inputs: the argument total is workspace
    grads = min(temp, max(0.0, float(grad_bytes)))
    classes["gradients"] = grads
    classes["activations"] = (temp - grads) + (out - alias) + spill
    classes["executable"] = code
    return MemoryProfile(
        peak_bytes=_peak_from_stats(stats),
        bytes_by_class=classes,
        stats=dict(stats),
        top_buffers=list(top_buffers),
        chain_length=chain_length,
    )


def _abstract_tree(tree) -> Any:
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(tuple(x.shape), x.dtype), tree
    )




def analyze_step_memory(
    engine,
    state,
    batch,
    *,
    donate: bool = True,
    chain_length: int | None = None,
    top_k: int = 10,
) -> MemoryProfile:
    """Attribute the peak HBM of the engine's real step program.

    ``batch`` is the PER-STEP batch (arrays or ``ShapeDtypeStruct``s);
    ``chain_length=N`` analyzes the chained-window program over the
    chain-stacked batch instead (N global batches live at once). ``donate``
    mirrors the dispatch path's donation by default — the program whose fit
    matters is the one the trainer runs. Lowering happens on abstract avals
    via ``TrainEngine.compile_step_probe`` (memoized; no device execution,
    no trace-count side effects). Raises ``ValueError`` when the backend
    reports no memory analysis — callers degrade, never guess."""
    batch = _abstract_tree(batch)
    probe_batch = (
        stack_chain_batch(batch, chain_length) if chain_length else batch
    )
    compiled = engine.compile_step_probe(
        state, probe_batch, donate=donate, chain_length=chain_length
    )
    stats = memory_stats_dict(compiled)
    if stats is None:
        raise ValueError(
            "backend reports no memory analysis for the compiled step — "
            "memory attribution unavailable on this platform"
        )
    # Per-DEVICE input bytes, sized by the engine's own layouts: an SPMD
    # program's memory_analysis() reports the per-device module (an
    # fsdp-sharded param contributes bytes/extent, a data-sharded batch
    # rows/extent), so the classable input sum must use shard shapes or the
    # pro-rata partition would skew toward whichever class shards least.
    # On a pure-DP mesh the state tree is replicated (shard == global) and
    # only the batch shrinks — which is also what XLA reports.
    from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

    state_sh = engine.state_sharding_tree(state)
    batch_sh = (
        mesh_lib.chain_batch_sharding(engine.mesh)
        if chain_length
        else mesh_lib.batch_sharding(engine.mesh)
    )
    input_classes = dict(state_class_bytes(state, state_sh))
    input_classes["input_batch"] = batch_class_bytes(probe_batch, batch_sh)
    # The grad tree mirrors the params; under fsdp sharding XLA's
    # reduce-scatter keeps per-device grad residency at the shard size, so
    # the gradients-class cap is the per-device param bytes.
    grad_bytes = _tree_bytes(
        getattr(state, "params", None), getattr(state_sh, "params", None)
    )
    top = (
        top_buffers_from_hlo(compiled.as_text(), top_k) if top_k > 0 else []
    )
    return attribute_memory(
        stats,
        input_classes,
        grad_bytes,
        top_buffers=top,
        chain_length=chain_length,
    )
