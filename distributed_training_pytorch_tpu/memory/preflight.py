"""OOM pre-flight: predict fit *before* the first dispatch, and when the
configured program cannot fit, say what would.

Every historical OOM hunt in this repo was trial-and-error on real
hardware: shrink the batch, re-launch, wait out the compile, crash again.
The whole loop is computable host-side — ``compiled.memory_analysis()`` of
the abstractly-lowered program predicts peak bytes without executing
anything — so the preflight turns it into one structured report:

* predict the configured program's peak (chained window included — that IS
  the dispatched program) via :func:`memory.analysis.analyze_step_memory`;
* compare against per-device capacity minus a headroom margin
  (fragmentation, collectives scratch, the allocator's own slack);
* on predicted OOM, **bisect over abstract lowerings** for the largest
  batch that fits, probe doubling grad-accumulation factors for the
  smallest microbatch split that keeps the full batch, and (on a pure
  batch-parallel mesh) probe ``TrainEngine.with_mesh`` twins for the
  smallest ``fsdp=N`` whose per-device peak fits (ZeRO-3 sharding of
  params + optimizer state — docs/parallelism.md) — then fail fast
  (``action="raise"``) with the recommendations in the error, before any
  device ever allocates a byte.

Sharded programs are sized in PER-DEVICE shard bytes end to end: the SPMD
executable's ``memory_analysis()`` reports the per-device module, and the
attribution layer (``memory.analysis``) sizes every input leaf at its
shard shape to match.

``Trainer(preflight=...)`` wires this in front of the first real compile;
``preflight=None`` (the default) reproduces the historical program exactly
(trace_counts + params parity, test-enforced — the telemetry/profiling
convention). Each bisection trial pays one abstract XLA compile; that
one-time cost is booked to the goodput ``compile`` bucket by the trainer.
"""

from __future__ import annotations

import dataclasses
import time

import jax

from distributed_training_pytorch_tpu.memory import analysis as mem_analysis
from distributed_training_pytorch_tpu.memory import live as mem_live
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

__all__ = [
    "Preflight",
    "PreflightOOMError",
    "PreflightReport",
    "resolve_preflight",
    "run_preflight",
]


class PreflightOOMError(RuntimeError):
    """Predicted OOM (``action="raise"``): the configured program does not
    fit device memory. ``.report`` carries the full :class:`PreflightReport`
    including the batch / microbatch recommendations."""

    def __init__(self, message: str, report: "PreflightReport | None" = None):
        super().__init__(message)
        self.report = report


@dataclasses.dataclass
class Preflight:
    """The ``Trainer(preflight=...)`` configuration bundle.

    * ``capacity_bytes`` — per-device memory budget. None = read
      ``bytes_limit`` from ``device.memory_stats()`` (TPU); on backends
      without stats (CPU) the fit check is skipped and the prediction is
      still recorded/emitted;
    * ``headroom``       — fraction of capacity held back (fragmentation,
      collective scratch): the program must fit in
      ``capacity * (1 - headroom)``;
    * ``action``         — ``"raise"`` (default: fail fast before dispatch)
      or ``"warn"`` (log + event, train on — for runs probing the boundary);
    * ``recommend``      — bisect for the max fitting batch and probe
      microbatch factors on predicted OOM (each trial = one abstract
      compile);
    * ``top_k``          — largest-buffer rows in the report;
    * ``max_trials``     — abstract-compile budget for the recommendation
      search.
    """

    capacity_bytes: int | None = None
    headroom: float = 0.1
    action: str = "raise"
    recommend: bool = True
    top_k: int = 8
    max_trials: int = 12

    def __post_init__(self):
        if self.action not in ("raise", "warn"):
            raise ValueError(f"action must be 'raise' or 'warn', got {self.action!r}")
        if not 0.0 <= float(self.headroom) < 1.0:
            raise ValueError(f"headroom must be in [0, 1), got {self.headroom!r}")


def resolve_preflight(spec) -> Preflight | None:
    """Trainer-knob resolution (the ``resolve_telemetry`` convention):
    ``None``/``False`` = off — the historical program, byte-for-byte;
    ``True``/``"on"``/``"check"`` = defaults; a :class:`Preflight` instance
    passes through."""
    if spec is None or spec is False:
        return None
    if spec is True:
        return Preflight()
    if isinstance(spec, str):
        key = spec.lower()
        if key in ("on", "1", "true", "check", "default"):
            return Preflight()
        if key in ("off", "0", "false", "none"):
            return None
        raise ValueError(
            f"unknown preflight spec {spec!r} (use 'on', 'off', or a Preflight)"
        )
    if isinstance(spec, Preflight):
        return spec
    raise TypeError(
        f"preflight must be None, bool, str, or Preflight, got {type(spec)}"
    )


@dataclasses.dataclass
class PreflightReport:
    """The structured verdict. ``fits`` is None when capacity is unknown
    (prediction recorded, check skipped). Recommendations are populated
    only on predicted OOM: ``recommended_batch`` is the largest global
    batch (a multiple of the mesh's batch-shard granularity) whose
    predicted peak fits; ``recommended_accum`` the smallest
    grad-accumulation factor that fits the FULL configured batch (None
    where no candidate fits / divides)."""

    predicted_peak_bytes: int
    batch_size: int
    profile: mem_analysis.MemoryProfile
    capacity_bytes: int | None = None
    usable_bytes: int | None = None
    headroom: float = 0.0
    fits: bool | None = None
    chain_length: int | None = None
    recommended_batch: int | None = None
    recommended_accum: int | None = None
    recommended_fsdp: int | None = None
    trials: int = 0
    seconds: float = 0.0

    def to_fields(self) -> dict:
        """Flat JSON-safe payload for the ``memory_preflight`` event."""
        fields = {
            "fits": self.fits,
            "batch_size": self.batch_size,
            "capacity_bytes": self.capacity_bytes,
            "usable_bytes": self.usable_bytes,
            "headroom": self.headroom,
            "recommended_batch": self.recommended_batch,
            "recommended_accum": self.recommended_accum,
            "recommended_fsdp": self.recommended_fsdp,
            "trials": self.trials,
            "seconds": round(self.seconds, 3),
            "top_buffers": self.profile.top_buffers[:5],
            **self.profile.to_fields(),
        }
        return fields


def _leading_dim(batch) -> int:
    leaves = jax.tree.leaves(batch)
    if not leaves:
        raise ValueError("preflight: batch tree has no leaves")
    return int(leaves[0].shape[0])


def _batch_shard(mesh) -> int:
    """The batch-dim sharding granularity: global batches must be multiples
    of the mesh extent over the batch axes (``parallel.mesh.batch_sharding``
    shards dim 0 over data x fsdp) — the ONE definition, shared with the
    Trainer's ctor divisibility check."""
    return mesh_lib.batch_shard_extent(mesh)


def _resize_batch(batch, new_leading: int):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            (int(new_leading),) + tuple(x.shape[1:]), x.dtype
        ),
        batch,
    )


def _format_bytes(n: int | float | None) -> str:
    if n is None:
        return "unknown"
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(value) < 1024.0 or unit == "GiB":
            return f"{value:.2f} {unit}" if unit != "B" else f"{int(value)} B"
        value /= 1024.0
    return f"{value:.2f} GiB"


def run_preflight(
    engine,
    state,
    batch,
    config: Preflight,
    *,
    chain_length: int | None = None,
    log=None,
    events=None,
) -> "PreflightReport | None":
    """Predict, check, recommend. ``batch`` is the PER-STEP global batch
    (arrays or ``ShapeDtypeStruct``s); ``chain_length`` analyzes the
    chained-window program when the trainer dispatches windows. ``events``
    (an ``EventLog`` or None) receives one ``memory_preflight`` record;
    ``log`` the trainer's ``log(msg, log_type)`` closure. Raises
    :class:`PreflightOOMError` on predicted OOM under ``action="raise"``.

    Returns None (with a warning and a ``skipped`` event) when the backend
    exposes no ``memory_analysis`` at all — an observability knob must
    degrade on an unsupported platform, never kill training."""
    say = log if log is not None else (lambda msg, log_type="info": None)
    t0 = time.perf_counter()
    try:
        profile = mem_analysis.analyze_step_memory(
            engine, state, batch, chain_length=chain_length, top_k=config.top_k
        )
    except ValueError as e:
        say(f"memory preflight skipped: {e}", "warning")
        if events is not None:
            events.emit("memory_preflight", skipped=True, reason=str(e))
        return None
    report = PreflightReport(
        predicted_peak_bytes=profile.peak_bytes,
        batch_size=_leading_dim(batch),
        profile=profile,
        headroom=float(config.headroom),
        chain_length=chain_length,
    )
    capacity = config.capacity_bytes
    if capacity is None:
        capacity = mem_live.device_capacity_bytes()
    if capacity is None:
        say(
            "memory preflight: device reports no capacity (memory_stats "
            f"absent on this backend) — predicted peak "
            f"{_format_bytes(profile.peak_bytes)} recorded, fit check skipped",
            "warning",
        )
    else:
        report.capacity_bytes = int(capacity)
        report.usable_bytes = int(capacity * (1.0 - config.headroom))
        report.fits = profile.peak_bytes <= report.usable_bytes
        if not report.fits and config.recommend:
            _recommend(engine, state, batch, config, report, chain_length)
    report.seconds = time.perf_counter() - t0
    if events is not None:
        events.emit("memory_preflight", **report.to_fields())
    if report.fits is False:
        message = _failure_message(report)
        if config.action == "raise":
            raise PreflightOOMError(message, report=report)
        say(message, "warning")
    elif report.fits:
        say(
            f"memory preflight: predicted peak "
            f"{_format_bytes(profile.peak_bytes)} fits "
            f"{_format_bytes(report.usable_bytes)} usable "
            f"({_format_bytes(report.capacity_bytes)} capacity, "
            f"{config.headroom:.0%} headroom)"
        )
    return report


def _predict(engine, state, batch, chain_length, report) -> int:
    """One recommendation trial = one THROWAWAY abstract compile.
    Deliberately not ``engine.compile_step_probe``: the probe cache
    memoizes loaded executables per shape for the process lifetime, and
    under ``action="warn"`` (a run deliberately probing the boundary — on
    a memory-constrained device, exactly when it matters) up to
    ``max_trials`` never-again-used executables would stay resident. The
    accum trials' ``with_accum`` twins are throwaway for the same reason."""
    report.trials += 1
    probe_batch = (
        mem_analysis.stack_chain_batch(batch, chain_length) if chain_length else batch
    )
    compiled = engine.lower_step_probe(
        state, probe_batch, donate=True, chain_length=chain_length
    ).compile()
    peak = mem_analysis.predicted_peak_bytes(compiled)
    if peak is None:  # unreachable: the initial analysis on this backend succeeded
        raise ValueError("backend stopped reporting memory analysis mid-preflight")
    return peak


def _recommend(engine, state, batch, config, report, chain_length) -> None:
    """Populate ``recommended_batch`` / ``recommended_accum``. Peak memory
    is monotone in batch size (activations and the staged input grow with
    it; everything else is constant), so bisection over the shard-multiple
    grid finds the exact boundary in log2 trials."""
    usable = report.usable_bytes
    shard = _batch_shard(engine.mesh)
    full = report.batch_size
    # -- max fitting batch (bisection over multiples of the shard size) ---
    if full > shard and report.trials < config.max_trials:
        if _predict(engine, state, _resize_batch(batch, shard), chain_length, report) <= usable:
            lo, hi = 1, full // shard  # lo*shard fits, hi*shard does not
            while hi - lo > 1 and report.trials < config.max_trials:
                mid = (lo + hi) // 2
                peak = _predict(
                    engine, state, _resize_batch(batch, mid * shard), chain_length, report
                )
                if peak <= usable:
                    lo = mid
                else:
                    hi = mid
            report.recommended_batch = lo * shard
    # -- smallest microbatch factor keeping the full batch ---------------
    factor = 2
    base_accum = max(1, int(engine.accum_steps))
    while report.trials < config.max_trials:
        accum = base_accum * factor
        micro = full // accum
        if micro < 1 or full % accum or micro % shard:
            break
        trial_engine = engine.with_accum(accum)
        if _predict(trial_engine, state, batch, chain_length, report) <= usable:
            report.recommended_accum = accum
            break
        factor *= 2
    # -- smallest fsdp extent that fits (ZeRO-3: shard params + opt state)
    # Probed on with_mesh twins that split the current data axis into
    # data x fsdp: the batch-shard extent (data x fsdp product) is
    # unchanged, so the same global batch divides, and per-device peak
    # falls as param/optimizer shards shrink. Only attempted on a pure
    # batch-parallel mesh (re-planning a tensor/pipe/expert mesh is an
    # operator decision, not a preflight guess).
    mesh = engine.mesh
    data = int(mesh.shape.get(mesh_lib.DATA_AXIS, 1))
    replanable = data > 1 and all(
        int(extent) == 1
        for axis, extent in mesh.shape.items()
        if axis != mesh_lib.DATA_AXIS
    )
    if replanable:
        # Every divisor of the data extent is a legal fsdp split (doubling
        # would dead-end at the first non-dividing power of two — data=12
        # can shard 2/3/4/6/12-ways, not just 2 and 4). Smallest first:
        # the least-disruptive mesh change that fits wins.
        for fsdp in sorted(
            f for f in range(2, data + 1) if data % f == 0
        ):
            if report.trials >= config.max_trials:
                break
            trial_mesh = mesh_lib.create_mesh(
                {mesh_lib.DATA_AXIS: data // fsdp, mesh_lib.FSDP_AXIS: fsdp},
                devices=list(mesh.devices.flat),
            )
            twin = engine.with_mesh(trial_mesh)
            if _predict(twin, state, batch, chain_length, report) <= usable:
                report.recommended_fsdp = fsdp
                break


def _failure_message(report: PreflightReport) -> str:
    lines = [
        "memory preflight: predicted OOM — "
        f"peak {_format_bytes(report.predicted_peak_bytes)} exceeds "
        f"{_format_bytes(report.usable_bytes)} usable "
        f"({_format_bytes(report.capacity_bytes)} capacity - "
        f"{report.headroom:.0%} headroom) "
        f"at global batch {report.batch_size}"
        + (f", chained x{report.chain_length}" if report.chain_length else ""),
    ]
    fractions = report.profile.fractions()
    split = ", ".join(
        f"{cls} {_format_bytes(report.profile.bytes_by_class[cls])} "
        f"({fractions[cls]:.0%})"
        for cls in mem_analysis.BUFFER_CLASSES
        if report.profile.bytes_by_class.get(cls)
    )
    lines.append(f"  attribution: {split}")
    if report.recommended_batch is not None:
        lines.append(
            f"  recommendation: batch {report.recommended_batch} fits "
            f"(largest shard-aligned batch under the limit, "
            f"{report.trials} abstract lowerings)"
        )
    if report.recommended_accum is not None:
        lines.append(
            f"  recommendation: accum_steps={report.recommended_accum} fits the "
            f"full batch {report.batch_size} (microbatch "
            f"{report.batch_size // report.recommended_accum})"
        )
    if report.recommended_fsdp is not None:
        lines.append(
            f"  recommendation: enable fsdp={report.recommended_fsdp} — "
            f"Trainer(mesh=MeshConfig(fsdp={report.recommended_fsdp}).build()) "
            "shards params + optimizer state per-device at the same global "
            "batch (predicted to fit; docs/parallelism.md)"
        )
    if (
        report.recommended_batch is None
        and report.recommended_accum is None
        and report.recommended_fsdp is None
    ):
        lines.append(
            "  no fitting configuration found (params + optimizer state may "
            "exceed capacity outright — shard the model over more chips: "
            "MeshConfig(fsdp=...), docs/parallelism.md)"
        )
    return "\n".join(lines)
