"""Jitted train/eval step engine.

TPU-native replacement for the reference hot path (``trainer/trainer.py:143-156``
+ ``example_trainer.py:73-89``): where the reference does per-batch H2D copy,
DDP forward, backward with bucketed NCCL all-reduce, optimizer step, and a
``loss.item()`` device sync *per step*, this engine compiles the whole step —
loss, ``jax.grad``, cross-device gradient reduction, and the optax update —
into one XLA program over a named mesh. Gradient synchronization needs no
explicit collective: the batch is sharded over the ``data`` axis and XLA
inserts (and overlaps) the all-reduce itself. Params are replicated for pure
DP, or sharded over ``fsdp``/``tensor`` axes per ``parallel.sharding`` rules
(ZeRO-3 / Megatron-TP analogs) — the step body is identical either way; only
the sharding annotations change. Metrics stay on device; the host never
blocks per step.

Gradient accumulation (BASELINE config 5) runs as a ``lax.scan`` over
microbatches inside the same compiled step.

Mixed precision (ISSUE 3): a ``precision.Policy`` casts params and float
inputs to its compute dtype at the loss-fn boundary INSIDE the compiled step
— master weights, grads, and optimizer state stay in ``param_dtype`` (fp32)
because the grads of the uncast params flow back through the cast's
transpose. Loss scaling (``precision.loss_scale``) rides in
``state.loss_scale``: the loss is multiplied by the scale before ``grad``,
grads divided after, and a ``DynamicScale`` folds torch.amp's grow/backoff/
skip protocol into the same non-finite guard ``nan_guard`` uses, so an
overflow-skip and a nan-skip are one event counted once. The default fp32
policy is detected statically and traces the exact pre-precision program.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_training_pytorch_tpu import compat
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel import sharding as sharding_lib
from distributed_training_pytorch_tpu.precision import get_policy, is_dynamic
from distributed_training_pytorch_tpu.train.state import TrainState

# A LossFn maps (params, model_state, batch, rng, train) ->
#   (loss, (metrics dict, new_model_state)).
LossFn = Callable[[Any, Any, Any, jax.Array, bool], tuple[jax.Array, tuple[Mapping, Any]]]


class NonFiniteLossError(FloatingPointError):
    """Raised by the trainer's ``nan_policy="raise"`` when a step produced a
    non-finite loss (the functional analog of torch's anomaly detection)."""


def stack_chain_batch(batch, chain_length: int) -> Any:
    """The chain-stacked abstract window for a per-step batch: every leaf
    gains a leading ``chain_length`` axis (the ``device_prefetch_chained``
    staging layout the chained program consumes). The ONE stacking rule for
    every observability probe of the chained program — memory attribution
    (``memory.analysis``), the donation audit (``analysis.hlo_audit``), and
    the communication audit (``analysis.comm_audit``) all build the probe
    window here, so the audited window shape cannot drift from the shape
    :meth:`TrainEngine.train_steps_chained` dispatches."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((int(chain_length),) + tuple(x.shape), x.dtype),
        batch,
    )


def xla_flag_options(flags: str | None) -> dict[str, str]:
    """Parse an ``XLA_FLAGS``-style string into a ``compiler_options`` dict
    for :meth:`TrainEngine.compile_train_step` /
    :meth:`TrainEngine.compile_chained_train_steps`.

    ``"--xla_a=true --xla_b=2"`` -> ``{"xla_a": "true", "xla_b": "2"}``; a
    bare ``--xla_flag`` maps to ``"true"``. This is the bridge the autotuner
    (``train/autotune.py``) uses to sweep latency-hiding / async-collective
    flags per-compile instead of mutating the global ``XLA_FLAGS`` env, which
    only applies at backend init — a sweep that restarts the process per
    candidate would pay compile + init for every flag set and could never
    share one warm engine.
    """
    options: dict[str, str] = {}
    for tok in (flags or "").split():
        if not tok.startswith("--"):
            raise ValueError(f"XLA flag {tok!r} must start with '--'")
        key, eq, value = tok[2:].partition("=")
        if not key.startswith("xla"):
            raise ValueError(f"{tok!r} is not an --xla_* flag")
        options[key] = value if eq else "true"
    return options


def make_supervised_loss(model, criterion: Callable) -> LossFn:
    """Build the standard supervised LossFn from a Flax module + criterion.

    ``criterion(outputs, batch) -> (loss, metrics)`` is the functional analog of
    the reference's ``build_criterion`` hook (``example_trainer.py:55-58``);
    the returned metrics dict mirrors the ``{"ce_loss": ...}`` contract of
    ``train_step`` (``example_trainer.py:89``).
    """

    def loss_fn(params, model_state, batch, rng, train):
        variables = {"params": params, **model_state}
        mutable = list(model_state) if train else []
        kwargs = {"mutable": mutable} if mutable else {}
        if train:
            # dropout + droppath (stochastic depth, ConvNeXt) streams; Flax
            # ignores streams a model doesn't declare.
            kwargs["rngs"] = {"dropout": rng, "droppath": jax.random.fold_in(rng, 1)}
        out = model.apply(variables, batch["image"], train=train, **kwargs)
        outputs, new_model_state = out if mutable else (out, model_state)
        loss, metrics = criterion(outputs, batch)
        return loss, (metrics, new_model_state)

    return loss_fn


class TrainEngine:
    """Owns the compiled train/eval steps and the state layout on the mesh.

    Collapses the reference's four mutable hooks (model/criterion/optimizer/
    scheduler, ``trainer/trainer.py:38-41``) into: a ``LossFn``, an optax
    ``GradientTransformation`` (optimizer + schedule fused), and a mesh.
    """

    def __init__(
        self,
        loss_fn: LossFn,
        optimizer: optax.GradientTransformation,
        mesh: Mesh,
        *,
        accum_steps: int = 1,
        schedule: optax.Schedule | None = None,
        donate_state: bool = True,
        sharding_rules: Sequence | None = None,
        fsdp_min_size: int = 2**18,
        nan_guard: bool = False,
        precision=None,
        loss_scale=None,
        stats: bool = False,
    ):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.accum_steps = int(accum_steps)
        self.schedule = schedule
        # Mixed precision: the policy is static (trace-time) config; the
        # loss-scale STATE lives in TrainState (init_state seeds it with this
        # initial value) so it survives checkpoint/resume and chained scans.
        self.precision = get_policy(precision)
        self.initial_loss_scale = loss_scale
        # Non-finite step guard (graceful-degradation support): when on, a
        # step whose loss or grads contain NaN/Inf leaves params/opt_state/
        # model_state UNTOUCHED (step and rng still advance, so the data and
        # dropout streams move past the poison batch) and reports
        # metrics["nonfinite"]=1. All inside the compiled step — no host
        # sync. Off by default: the where-select touches every state leaf.
        self.nan_guard = bool(nan_guard)
        # Telemetry train-health stats (ISSUE 4): grad/param norms, update
        # ratio, nonfinite flag computed INSIDE the step and returned as
        # ordinary metrics — they ride chained windows as scan outputs with
        # zero extra host syncs, and reading the dataflow (norm reductions
        # hang off grads/params/updates, never feed back into them) keeps
        # params bit-exact with a stats-off run. Off by default: the
        # historical program traces byte-identically.
        self.stats = bool(stats)
        self.sharding_rules = sharding_rules
        self.fsdp_min_size = fsdp_min_size
        self._batch_sharding = mesh_lib.batch_sharding(mesh)
        self._replicated = NamedSharding(mesh, P())
        self._donate = (0,) if donate_state else ()
        # Param/opt-state sharding tree — computed from the state structure on
        # first use (init_state or the first step); replicated for pure DP,
        # rule/FSDP-sharded otherwise (parallel.sharding).
        self._state_sharding = None
        self._state_structure = None
        self._train_step = None
        self._eval_step = None
        # Chained executables, one per window length (jit itself caches per
        # input shape, so a given length never retraces for the same batch
        # shapes). Tail windows shorter than the chain length are the
        # trainer's job to run single-step — compiling a fresh chain per tail
        # length would pay a full-model compile for one window.
        self._chained_fns: dict[int, Any] = {}
        # Compilation counters: bumped once per TRACE of each compiled body
        # (a jit cache hit does not re-execute the Python body). The
        # scripts/retrace_guard.py CI gate asserts these stay at 1 per shape,
        # so a dispatch-path change that silently retraces fails fast.
        self.trace_counts: Counter = Counter()
        # Memoized observability probe executables (compile_step_probe),
        # keyed by abstract shapes: the MFU probe and the profile capture's
        # roofline join both want the identical program — one compile serves
        # both.
        self._step_probe_cache: dict = {}

    def state_sharding(self, state_or_abstract) -> Any:
        """The NamedSharding tree this engine lays state out with.

        Contract: one engine serves ONE state — the tree is computed from the
        first state seen (init_state or the first step), cached, and later
        calls must present the same tree structure AND leaf shapes/dtypes (a
        second model/state on a reused engine would otherwise silently get the
        first one's shardings: at best a cryptic XLA error, at worst wrong
        layouts)."""
        # str(dtype) rather than result_type: typed PRNG-key leaves carry an
        # extended dtype that result_type rejects.
        leaf_shapes = jax.tree.map(
            lambda x: (tuple(x.shape), str(getattr(x, "dtype", None))), state_or_abstract
        )
        structure = (jax.tree.structure(state_or_abstract), tuple(jax.tree.leaves(leaf_shapes)))
        if self._state_sharding is None:
            self._state_structure = structure
            if self.sharding_rules is None and not any(
                self.mesh.shape.get(a, 1) > 1 for a in (mesh_lib.FSDP_AXIS, mesh_lib.TENSOR_AXIS)
            ):
                self._state_sharding = self._replicated
            else:
                self._state_sharding = sharding_lib.state_shardings(
                    state_or_abstract,
                    self.mesh,
                    self.sharding_rules or (),
                    fsdp_min_size=self.fsdp_min_size,
                )
        elif structure != self._state_structure:
            raise ValueError(
                "this TrainEngine is already bound to a state with a "
                "different structure or leaf shapes/dtypes (one engine serves "
                "one model/state); build a new engine for the new state."
            )
        return self._state_sharding

    def state_sharding_tree(self, state_or_abstract) -> Any:
        """:meth:`state_sharding` expanded to one ``NamedSharding`` per leaf
        (pure DP returns a SINGLE replicated sharding there — consumers that
        need per-leaf shard shapes, like the memory subsystem's per-device
        byte accounting and the checkpoint sharding record, want the
        broadcast tree)."""
        return sharding_lib.expand_shardings(
            state_or_abstract, self.state_sharding(state_or_abstract)
        )

    def _build_steps(self, state) -> None:
        if self._train_step is not None:
            return
        state_sharding = self.state_sharding(state)

        def train_step(state, batch):
            self.trace_counts["train_step"] += 1
            return self._train_step_impl(state, batch)

        def eval_step(state, batch):
            self.trace_counts["eval_step"] += 1
            return self._eval_step_impl(state, batch)

        self._train_step = jax.jit(
            train_step,
            in_shardings=(state_sharding, self._batch_sharding),
            out_shardings=(state_sharding, self._replicated),
            donate_argnums=self._donate,
        )
        self._eval_step = jax.jit(  # jaxlint: disable=missing-donate-on-jit -- eval only READS state: donating would consume the very buffers the next train step needs
            eval_step,
            in_shardings=(state_sharding, self._batch_sharding),
            out_shardings=self._replicated,
        )

    # -- state ------------------------------------------------------------

    def init_state(self, rng: jax.Array, init_fn: Callable[[jax.Array], dict]) -> TrainState:
        """Initialize state directly into this engine's sharded layout.

        ``init_fn(rng) -> variables`` (a Flax ``model.init`` closure). The
        analog of ``build_model`` + ``model.to(local_rank)`` + the DDP ctor's
        initial parameter broadcast (``trainer/trainer.py:38,51-52``) — init
        is jitted with the engine's state sharding as output sharding:
        replicated for pure DP (every device holds identical params, no
        explicit broadcast), or fsdp/tensor-sharded per the engine's rules —
        in which case NO device ever holds the full parameter set.
        """
        init_rng, state_rng = jax.random.split(rng)

        def make(init_rng, state_rng):
            variables = init_fn(init_rng)
            params = variables.pop("params")
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.optimizer.init(params),
                model_state=dict(variables),
                rng=state_rng,
                loss_scale=self.initial_loss_scale,
            )

        # Shape-infer the state, derive its sharding tree, then materialize
        # directly into that layout — params larger than one device's HBM
        # never exist unsharded anywhere.
        abstract = jax.eval_shape(make, init_rng, state_rng)
        out_shardings = self.state_sharding(abstract)
        with self._ambient_mesh():  # in-model constraints resolve (see below)
            return jax.jit(make, out_shardings=out_shardings)(init_rng, state_rng)

    # -- compiled bodies --------------------------------------------------

    def _wrap_loss(self, scale_state):
        """The loss-fn boundary where mixed precision happens: cast params +
        float inputs to the policy's compute dtype, cast the loss back to
        fp32, and multiply by the loss scale so ``grad`` differentiates the
        SCALED loss. Aux carries the raw (unscaled, fp32) loss for metrics.

        With the fp32 policy and no dynamic scale this is a pure aux
        restructure — zero ops added, the compiled program is bit-identical
        to the pre-precision engine (test-enforced)."""
        policy = self.precision
        base = self.loss_fn
        dynamic = is_dynamic(scale_state)
        if not policy.active and not dynamic:
            def wrapped(params, model_state, batch, rng, train):
                loss, (metrics, new_ms) = base(params, model_state, batch, rng, train)
                return loss, (loss, metrics, new_ms)

            return wrapped

        def wrapped(params, model_state, batch, rng, train):
            loss, (metrics, new_ms) = base(
                policy.cast_params(params),
                model_state,
                policy.cast_inputs(batch),
                rng,
                train,
            )
            loss = policy.cast_output(loss)
            grad_loss = scale_state.scale_loss(loss) if dynamic else loss
            return grad_loss, (loss, metrics, new_ms)

        return wrapped

    def _grads_and_metrics(self, state: TrainState, batch, rng):
        scale_state = state.loss_scale
        dynamic = is_dynamic(scale_state)
        grad_fn = jax.value_and_grad(self._wrap_loss(scale_state), has_aux=True)
        if self.accum_steps <= 1:
            (_, (loss, metrics, new_ms)), grads = grad_fn(
                state.params, state.model_state, batch, rng, True
            )
            if dynamic:
                grads = scale_state.unscale_grads(grads)
            return grads, loss, metrics, new_ms

        # Microbatch scan: reshape [B, ...] -> [A, B/A, ...] and accumulate.
        def to_micro(x):
            return x.reshape((self.accum_steps, x.shape[0] // self.accum_steps) + x.shape[1:])

        micro = jax.tree.map(to_micro, batch)

        def body(carry, xs):
            mb, micro_idx = xs
            grads_acc, loss_acc, metrics_acc, ms = carry
            mb_rng = jax.random.fold_in(rng, micro_idx)
            (_, (loss, metrics, ms)), grads = grad_fn(state.params, ms, mb, mb_rng, True)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            loss_acc = loss_acc + loss
            metrics_acc = jax.tree.map(jnp.add, metrics_acc, dict(metrics))
            return (grads_acc, loss_acc, metrics_acc, ms), None

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        # Trace one microbatch to learn the metrics structure for the carry.
        _, (metrics0, _) = jax.eval_shape(
            lambda p, ms, b: self.loss_fn(p, ms, b, rng, True),
            state.params,
            state.model_state,
            jax.tree.map(lambda x: x[0], micro),
        )
        zero_metrics = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), dict(metrics0))
        (grads, loss, metrics, new_ms), _ = jax.lax.scan(
            body,
            (zero_grads, jnp.zeros(()), zero_metrics, state.model_state),
            (micro, jnp.arange(self.accum_steps)),
        )
        if dynamic:
            grads = scale_state.unscale_grads(grads)  # accumulated scaled
        inv = 1.0 / self.accum_steps
        grads = jax.tree.map(lambda g: g * inv, grads)
        metrics = jax.tree.map(lambda m: m * inv, metrics)
        return grads, loss * inv, metrics, new_ms

    def _train_step_impl(self, state: TrainState, batch):
        step_rng = jax.random.fold_in(state.rng, state.step)
        grads, loss, metrics, new_ms = self._grads_and_metrics(state, batch, step_rng)
        updates, new_opt_state = self.optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        if self.stats:
            from distributed_training_pytorch_tpu.telemetry.stats import (
                train_health_stats,
            )

            # setdefault: a user criterion that already reports one of these
            # keys wins; the guard below overwrites `nonfinite` with its
            # exact per-leaf predicate when armed (the stats flag derives
            # from the reduced grad norm — same answer on real poison, but
            # the guard's version is the skip-accounting source of truth).
            for key, value in train_health_stats(
                loss=loss, grads=grads, params=state.params, updates=updates
            ).items():
                metrics.setdefault(key, value)
        scale_state = state.loss_scale
        dynamic = is_dynamic(scale_state)
        if self.nan_guard or dynamic:
            # ONE unified guard: a dynamic-scale overflow and a nan_policy
            # poison are the same predicate, the same conditional apply, and
            # the same metrics["nonfinite"] flag — a step is counted skipped
            # once, never twice.
            ok = jnp.isfinite(loss)
            for g in jax.tree.leaves(grads):
                ok &= jnp.all(jnp.isfinite(g))
            keep = lambda new, old: jax.tree.map(  # noqa: E731
                lambda n, o: jnp.where(ok, n, o), new, old
            )
            new_params = keep(new_params, state.params)
            new_opt_state = keep(new_opt_state, state.opt_state)
            new_ms = keep(new_ms, state.model_state)
            metrics["nonfinite"] = 1.0 - ok.astype(jnp.float32)
            if dynamic:
                # Grow/backoff runs inside the step; the scale THIS step used
                # is the observable metric (the post-adjust value is next
                # step's metric).
                scale_state = scale_state.adjust(ok)
                metrics["loss_scale"] = state.loss_scale.scale
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            model_state=new_ms,
            loss_scale=scale_state,
        )
        metrics.setdefault("loss", loss)
        if self.schedule is not None:
            metrics["lr"] = self.schedule(state.step)
        return new_state, metrics

    def _eval_step_impl(self, state: TrainState, batch):
        # Eval is deterministic (no dropout); the rng is passed only to keep
        # the LossFn signature uniform. The precision policy's boundary casts
        # apply to eval too (scale never does: no grads to protect).
        _, (_, metrics, _) = self._wrap_loss(None)(
            state.params, state.model_state, batch, state.rng, False
        )
        return dict(metrics)

    # -- public API -------------------------------------------------------

    def _ambient_mesh(self):
        """Make ``self.mesh`` the ambient mesh while tracing/dispatching.

        Models annotate internal layouts with bare ``PartitionSpec``s via
        ``with_sharding_constraint`` (e.g. ``parallel.moe``'s expert-sharded
        buffers) — those resolve against the ambient mesh, which plain
        ``jax.jit`` with explicit NamedShardings does NOT establish. Without
        this, in-model constraints would silently no-op on the engine path."""
        return compat.set_mesh(self.mesh)

    def train_step(self, state: TrainState, batch) -> tuple[TrainState, dict]:
        """One compiled optimizer step on a global batch. Metrics are device
        arrays (global means) — call ``jax.device_get`` only when logging."""
        self._build_steps(state)
        with self._ambient_mesh():
            return self._train_step(state, batch)

    def eval_step(self, state: TrainState, batch) -> dict:
        """Collective validation step — replaces the reference's rank-0-only,
        non-distributed ``validate`` (``trainer/trainer.py:184-206``): every
        device evaluates its shard and metrics reduce globally."""
        self._build_steps(state)
        with self._ambient_mesh():
            return self._eval_step(state, batch)

    def shard_batch(self, batch):
        """Host-local rows -> one global data-sharded array (see
        ``parallel.mesh.global_array_from_host_local``)."""
        return mesh_lib.global_array_from_host_local(batch, self.mesh)

    def train_steps_chained(self, state: TrainState, stacked_batch, length: int):
        """Run ``length`` train steps as ONE compiled on-device program.

        ``stacked_batch`` leaves carry a leading step axis of size ``length``
        (``parallel.mesh.chain_batch_sharding`` layout — the
        ``data.device_prefetch_chained`` staging format): a ``lax.scan``
        carries the state and slices one per-step batch per trip, so a single
        dispatch executes the whole window back-to-back on device. Per-step
        RNG still advances via ``state.step``, and the nan-guard and
        microbatch-accumulation paths run inside the scan body unchanged —
        chained execution is bit-identical to ``length`` sequential
        :meth:`train_step` calls on the same data (test-enforced).

        Returns ``(state, metrics)`` where every metric leaf has leading axis
        ``length`` — per-step values as scan outputs, so callers keep exact
        per-step accounting (loss logging, ``nonfinite`` counts) without any
        extra host sync.

        Executables are cached per ``length`` (and per shape, by jit): call
        with ONE window length and route shorter tails to :meth:`train_step`
        instead of paying a fresh full-model compile per tail length.
        """
        if length < 1:
            raise ValueError(f"length must be >= 1, got {length}")
        self._build_steps(state)
        fn = self._chained_step_fn(length, state)
        with self._ambient_mesh():
            return fn(state, stacked_batch)

    def _chained_step_fn(self, length: int, state_or_abstract):
        """The jitted chained-window program for ``length`` (built and cached
        on first use). Split out of :meth:`train_steps_chained` so the REAL
        dispatch program can be *lowered* on abstract avals without executing
        a window — which is how ``tests/test_analysis.py`` pins the static
        audit's chained probe (:meth:`lower_step_probe`; no trace-count side
        effects) byte-equal to this program: the audit verifies what the
        trainer actually runs, enforced rather than claimed."""
        fn = self._chained_fns.get(length)
        if fn is None:
            state_sharding = self.state_sharding(state_or_abstract)
            chain_sharding = mesh_lib.chain_batch_sharding(self.mesh)

            def chained(st, sbatch):
                self.trace_counts[f"chained_{length}"] += 1
                # _train_step_impl(state, batch) -> (state, metrics) is
                # exactly scan's (carry, x) -> (carry, y) contract; ys stack
                # into the per-step metrics. unroll=length: a rolled While
                # body reads its per-step batch through a dynamic-slice whose
                # layout can differ from the standalone step's input, and the
                # conv wgrad reduction order shifts by 1 ULP with it
                # (measured on CPU: 1 element of a VGG conv kernel after 4
                # steps) — unrolled windows reproduce the single-step program
                # bit-for-bit. Cost: compile time linear in `length`, the
                # right trade at the 4-32 window sizes chaining targets.
                return jax.lax.scan(
                    self._train_step_impl, st, sbatch, unroll=length
                )

            fn = jax.jit(
                chained,
                in_shardings=(state_sharding, chain_sharding),
                out_shardings=(state_sharding, self._replicated),
                donate_argnums=self._donate,
            )
            self._chained_fns[length] = fn
        return fn

    def unstack_window(self, stacked_batch, index: int):
        """Slice step ``index``'s batch out of a chain-stacked window, laid
        out as the single-step batch sharding — the trainer's fallback when a
        staged window must run step-by-step after all (fault injection
        active in its range)."""
        return jax.tree.map(
            lambda x: jax.device_put(x[index], self._batch_sharding), stacked_batch
        )

    def compile_train_step(self, state: TrainState, batch, *, compiler_options=None):
        """AOT-compile the train step for these shapes and return the compiled
        executable (callable as ``compiled(state, batch)``). Supported surface
        for benchmarking: ``compiled.cost_analysis()`` exposes XLA's FLOP
        estimate for MFU math.

        ``compiler_options`` passes per-compile XLA flags (e.g.
        ``{"xla_tpu_scoped_vmem_limit_kib": "49152"}`` — measured ~9% faster
        on the VGG16/v5e step; see utils/tpu.py) without touching global
        XLA_FLAGS."""
        self._build_steps(state)
        with self._ambient_mesh():
            lowered = self._train_step.lower(state, batch)
        if compiler_options:
            return lowered.compile(compiler_options=dict(compiler_options))
        return lowered.compile()

    def lower_step_probe(self, state, batch, *, donate: bool = False,
                         chain_length: int | None = None):
        """Lower (but do not compile) the observability probe — the
        pre-optimization module text (``.as_text()``) is what the static
        audit's precision-leak check reads: program *semantics* (a bf16
        policy's bf16 dots), where the compiled text on CPU shows the
        backend's f32-promotion of those same dots. See
        :meth:`compile_step_probe` for the donate/chain_length contract."""
        abstract_state, abstract_batch = jax.eval_shape(
            lambda s, b: (s, b), state, batch
        )
        state_sharding = self.state_sharding(state)
        if chain_length is None:
            fn = self._train_step_impl
            batch_sharding = self._batch_sharding
        else:
            if chain_length < 1:
                raise ValueError(f"chain_length must be >= 1, got {chain_length}")
            length = int(chain_length)

            def chained(st, sbatch):
                # The real chained window program (_chained_step_fn) minus
                # its trace-counting wrapper: same name, same scan, same
                # unroll, same shardings — lowered-HLO equality with the
                # dispatch program is pinned by test_analysis.py, so the two
                # constructions cannot drift apart silently.
                return jax.lax.scan(self._train_step_impl, st, sbatch, unroll=length)

            fn = chained
            batch_sharding = mesh_lib.chain_batch_sharding(self.mesh)
        probe = jax.jit(
            fn,
            in_shardings=(state_sharding, batch_sharding),
            out_shardings=(state_sharding, self._replicated),
            # Mirror the dispatch path's donation EXACTLY: an engine built
            # with donate_state=False runs undonated programs, and the
            # donation audit must see (and fail on) that program, not a
            # donated twin that never dispatches.
            donate_argnums=self._donate if donate else (),
        )
        with self._ambient_mesh():
            return probe.lower(abstract_state, abstract_batch)

    def compile_step_probe(self, state, batch, *, donate: bool = False,
                           chain_length: int | None = None):
        """Observability-only compiled copy of the train program (no
        counting wrapper) on abstract avals: one extra off-hot-path XLA
        compile, but the dispatch executables, their jit caches, and
        ``trace_counts`` are untouched — the retrace-guard contract holds
        with telemetry/profiling on (test-enforced). ``state``/``batch`` may
        be concrete arrays or ``ShapeDtypeStruct`` trees (no data is read).

        ``donate=False, chain_length=None`` (default) is the historical
        probe: the single step, undonated — feeds :meth:`step_cost_analysis`
        (the MFU probe) and the profile capture's per-op roofline join.
        ``donate=True`` mirrors the dispatch path's ``donate_argnums`` so the
        static audit (``analysis.hlo_audit``) can verify input-output buffer
        aliasing on the program the trainer actually runs; ``chain_length=N``
        probes the chained-window program (``batch`` then carries the leading
        step axis). Memoized per (abstract shape, donate, chain_length), so a
        run with both telemetry and profiling on pays each probe compile
        once, not once per consumer."""
        abstract_state, abstract_batch = jax.eval_shape(
            lambda s, b: (s, b), state, batch
        )
        leaves, treedef = jax.tree.flatten((abstract_state, abstract_batch))
        key = (
            treedef,
            tuple((leaf.shape, str(leaf.dtype)) for leaf in leaves),
            bool(donate),
            chain_length,
        )
        cached = self._step_probe_cache.get(key)
        if cached is not None:
            return cached
        compiled = self.lower_step_probe(
            state, batch, donate=donate, chain_length=chain_length
        ).compile()
        self._step_probe_cache[key] = compiled
        return compiled

    def with_accum(self, accum_steps: int) -> "TrainEngine":
        """An observability twin of this engine at a different
        grad-accumulation factor — same loss fn, optimizer, mesh, precision,
        guard, and donation, fresh jit caches. ``memory.preflight`` probes
        these (abstract lowerings only, never dispatched) to recommend the
        microbatch factor that fits device memory; the twin shares nothing
        with this engine's executables, so probing it cannot perturb the
        dispatch path."""
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        return TrainEngine(
            self.loss_fn,
            self.optimizer,
            self.mesh,
            accum_steps=accum_steps,
            schedule=self.schedule,
            donate_state=bool(self._donate),
            sharding_rules=self.sharding_rules,
            fsdp_min_size=self.fsdp_min_size,
            nan_guard=self.nan_guard,
            precision=self.precision,
            loss_scale=self.initial_loss_scale,
            stats=self.stats,
        )

    def with_mesh(self, mesh: Mesh) -> "TrainEngine":
        """An observability twin of this engine on a DIFFERENT mesh — same
        loss fn, optimizer, precision, guard, donation, sharding rules, and
        accumulation, fresh jit caches and a fresh state-sharding layout.
        ``memory.preflight`` probes these (abstract lowerings only, never
        dispatched) to answer "would this program fit with fsdp=N" — the
        sharded-fit recommendation on predicted OOM. The ``with_accum``
        contract holds: the twin shares nothing with this engine's
        executables, so probing it cannot perturb the dispatch path."""
        return TrainEngine(
            self.loss_fn,
            self.optimizer,
            mesh,
            accum_steps=self.accum_steps,
            schedule=self.schedule,
            donate_state=bool(self._donate),
            sharding_rules=self.sharding_rules,
            fsdp_min_size=self.fsdp_min_size,
            nan_guard=self.nan_guard,
            precision=self.precision,
            loss_scale=self.initial_loss_scale,
            stats=self.stats,
        )

    def step_cost_analysis(self, state, batch) -> dict:
        """XLA's cost analysis (FLOPs, bytes accessed, ...) of ONE train step
        for these shapes — the telemetry MFU probe, via
        :meth:`compile_step_probe`. The scan conventions match
        ``utils.hlo_flops``: for a chained run this single-step figure IS the
        per-step figure."""
        compiled = self.compile_step_probe(state, batch)
        from distributed_training_pytorch_tpu.utils.hlo_flops import xla_cost_analysis

        return xla_cost_analysis(compiled)

    def compile_chained_train_steps(
        self, state: TrainState, batch, length: int, *, compiler_options=None
    ):
        """AOT-compile ``length`` train steps chained on-device over one batch
        (``lax.scan`` carrying the state; per-step RNG still advances via
        ``state.step``). One dispatch then runs ``length`` real steps
        back-to-back — for measuring sustained device step time where
        per-dispatch host/relay latency would otherwise pollute the window
        (production pods dispatch locally at ~0.1 ms; a tunneled chip pays
        ~10-200 ms per call). Returns ``compiled(state, batch) -> (state,
        last_metrics)``."""
        if length < 1:
            raise ValueError(f"length must be >= 1, got {length}")
        self._build_steps(state)
        state_sharding = self.state_sharding(state)

        def chained(state, batch):
            def body(st, _):
                st, metrics = self._train_step_impl(st, batch)
                return st, metrics

            state, metrics = jax.lax.scan(body, state, None, length=length)
            return state, jax.tree.map(lambda m: m[-1], metrics)

        jitted = jax.jit(
            chained,
            in_shardings=(state_sharding, self._batch_sharding),
            out_shardings=(state_sharding, self._replicated),
            donate_argnums=self._donate,
        )
        with self._ambient_mesh():
            lowered = jitted.lower(state, batch)
        if compiler_options:
            return lowered.compile(compiler_options=dict(compiler_options))
        return lowered.compile()
