"""Functional training state.

The reference keeps mutable training state spread across ``self.model``,
``self.optimizer``, ``self.scheduler``, ``self.cur_epoch``
(``trainer/trainer.py:38-45``). The TPU-native design threads one immutable
pytree through a jitted step instead — XLA requires pure functions, and an
explicit state pytree is also exactly what gets checkpointed (the analog of the
snapshot dict at ``trainer/trainer.py:85-92``).
"""

from __future__ import annotations

from typing import Any

import jax
from flax import struct


@struct.dataclass
class TrainState:
    """Everything that evolves during training, as one pytree.

    * ``step``        — global optimizer step (scheduler position; the analog of
      the ``epoch`` counter saved at ``trainer/trainer.py:87``).
    * ``params``      — model parameters (``model.state_dict()`` analog).
    * ``opt_state``   — optax state (optimizer + scheduler state analog; optax
      schedules are functions of ``step`` so there is no separate scheduler
      state to save, unlike ``scheduler.state_dict()`` at ``:91``).
    * ``model_state`` — non-trainable collections (e.g. BatchNorm
      ``batch_stats`` for ResNet); empty dict for stateless models.
    * ``rng``         — PRNG key for dropout/augmentation; folded with ``step``
      each call so resume is deterministic.
    * ``loss_scale``  — mixed-precision loss-scale state (``precision.
      loss_scale``): ``None`` (default — no scaling, zero leaves, identical
      pytree behavior to the pre-precision layout), a ``NoOpScale`` (also
      zero leaves), or a ``DynamicScale`` whose scale/counter/skip scalars
      ride the state through the compiled step, chained windows, and
      checkpoint save/resume.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any
    rng: jax.Array
    loss_scale: Any = None

    def variables(self) -> dict:
        return {"params": self.params, **self.model_state}
