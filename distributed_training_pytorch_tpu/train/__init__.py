from distributed_training_pytorch_tpu.train.state import TrainState  # noqa: F401
from distributed_training_pytorch_tpu.train.engine import (  # noqa: F401
    NonFiniteLossError,
    TrainEngine,
    make_supervised_loss,
    stack_chain_batch,
    xla_flag_options,
)
