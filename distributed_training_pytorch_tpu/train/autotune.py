"""XLA-flag / schedule autotuner core — the candidate-measurement and
ranking library behind ``scripts/autotune.py`` (ISSUE 17).

Four flat bench rounds (BENCH_r02 -> r05) proved the measurement stack can
*detect* a stuck line (``telemetry.history.detect_flat_streaks``); this
module is the instrument that *moves* it. The shape is deliberate: every
piece reuses an existing, test-enforced implementation rather than growing a
private twin —

* **Timing** — ``time_chained`` is the two-length-differencing scan-chain
  timer that ``scripts/resnet_pallas_probe.py`` validated on real TPU relay
  latency (~0.1-0.3 s/dispatch cancels exactly), generalized to any
  ``f(*args)`` and hosted here so the probe imports it (test-enforced: the
  probe defines no private copy). ``measure_chained_step`` applies the same
  differencing to the REAL chained train-step executable
  (``TrainEngine.compile_chained_train_steps``) — candidates are ranked on
  the program that ships, not a proxy kernel.
* **Attribution** — every candidate-vs-baseline delta goes through
  ``profiling.diff.attribute_entry_delta`` (the run_compare/perf_gate
  implementation), so a winning config arrives with the same per-category
  evidence a regression would.
* **Refusal** — the PR 14 rule, adapted for deliberate sweeps: a candidate
  whose provenance CONFIG facets differ from the baseline's on any key it
  did NOT declare as swept (its ``knobs``) is refused, not ranked. Sweeping
  ``chain_steps`` legitimately changes that facet; a silently different
  ``dtype`` makes the comparison meaningless and must not produce a number.
* **Ranking** — lowest ``step_ms`` wins, but a win is *kept* only when it
  beats the baseline by more than ``FLAT_REL_TOL`` (the flat-streak
  detector's band): a "win" inside the noise band would re-flatten the bench
  line the next round and teach the tuner to chase noise.

The kept winner is committed as ``TUNED.json`` (``emit_tuned``); entries opt
in via ``tuned_defaults()`` under ``TUNED=1`` — autotuner off means no
behavior change anywhere (test-enforced).
"""

from __future__ import annotations

import functools
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

# A kept win must clear the flat-streak detector's band, or the next bench
# round lands back inside the r02->r05 streak it claims to end.
from distributed_training_pytorch_tpu.telemetry.history import FLAT_REL_TOL

__all__ = [
    "DEFAULT_TUNED_PATH",
    "Candidate",
    "emit_tuned",
    "load_tuned",
    "measure_chained_step",
    "rank_candidates",
    "time_chained",
    "tuned_defaults",
]

DEFAULT_TUNED_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "TUNED.json",
)


@dataclass
class Candidate:
    """One point in the declared sweep space.

    ``knobs`` is the candidate grammar (docs/performance.md "Autotuning"):

    * ``xla_flags`` — ``"--xla_..."`` string, applied per-compile via
      ``train.engine.xla_flag_options`` (never by mutating global XLA_FLAGS)
    * ``chain_steps`` — on-device steps per dispatch (lax.scan chain length)
    * ``batch`` / ``accum_steps`` — microbatch/accumulation shape
    * ``pallas`` — the unified kernel-policy knob (ops/dispatch.py)
    * ``block_rows`` — Pallas kernel tile knob (ops/pallas.py)

    Every key present in ``knobs`` is a *declared* swept facet: provenance
    disagreement on exactly those keys is expected and allowed; any other
    disagreement refuses the comparison (``rank_candidates``).
    """

    name: str
    knobs: dict = field(default_factory=dict)
    note: str = ""


def time_chained(f: Callable, *args, steps: int = 20, windows: int = 4,
                 perturb_arg: int = 1) -> float:
    """Per-call seconds for ``f(*args)`` by TWO-LENGTH DIFFERENCING: the
    relay's per-dispatch latency (~0.1-0.3 s — often 100x the op) is a
    constant per window, so time a short (``steps``) and a long
    (``5 * steps``) chain of the same scan body and divide the time
    difference by the extra trips; the dispatch constant cancels exactly.
    Best of ``windows`` windows per length.

    The scan body perturbs ``args[perturb_arg]`` by the carried output
    statistic (a data-dependent ~1e-30 scalar), so no iteration is
    loop-invariant — blocks hoisting and CSE without changing the math.
    This is the one timing implementation shared by the autotuner and
    ``scripts/resnet_pallas_probe.py`` (AST-test-enforced: the probe keeps
    no private copy).
    """
    import jax
    import jax.numpy as jnp

    def body(c, _):
        perturbed = list(args)
        a = args[perturb_arg]
        perturbed[perturb_arg] = (a.astype(jnp.float32) * (1.0 + c)).astype(a.dtype)
        out = f(*perturbed)
        # tiny, data-dependent carry: blocks loop-invariant hoisting and CSE
        return jnp.ravel(out)[:8].astype(jnp.float32).sum() * 1e-30, None

    @functools.partial(jax.jit, static_argnums=0)
    def chained(length, *call_args):
        c, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None, length=length)
        return c

    short, long_ = int(steps), 5 * int(steps)
    times = {}
    for length in (short, long_):
        _ = float(chained(length, *args))  # compile + warm (scalar sync)
        best = float("inf")
        for _w in range(int(windows)):
            t0 = time.perf_counter()
            _ = float(chained(length, *args))
            best = min(best, time.perf_counter() - t0)
        times[length] = best
    return (times[long_] - times[short]) / (long_ - short)


def measure_chained_step(
    engine,
    state,
    batch,
    *,
    chain_steps: int = 4,
    windows: int = 3,
    compiler_options: Mapping[str, str] | None = None,
    categories: bool = True,
) -> tuple[dict, Any]:
    """Measure one candidate's per-step milliseconds on the REAL chained
    train-step executable, with perf_gate-style category capture.

    Two-length differencing at the executable level: compile the
    ``chain_steps`` and ``5 * chain_steps`` chains (same avals, same
    ``compiler_options``), warm both, best-of-``windows`` each, and divide
    the window-time difference by the extra steps — per-dispatch host/relay
    latency cancels, leaving sustained device step time. The state is
    re-threaded through every call (donation-safe); the returned state is
    the post-measurement one.

    Returns ``(measurement, state)`` where measurement carries ``step_ms``
    plus the ``categories`` fractions of one traced extra window (degrading
    to no categories on any capture failure, exactly like perf_gate) — the
    two keys ``profiling.diff.attribute_entry_delta`` needs to pre-attribute
    any delta against this measurement.
    """
    import jax

    short, long_ = int(chain_steps), 5 * int(chain_steps)
    opts = dict(compiler_options) if compiler_options else None
    st = state
    times = {}
    compiled_long = None
    for length in (short, long_):
        compiled = engine.compile_chained_train_steps(
            st, batch, length, compiler_options=opts
        )
        if length == long_:
            compiled_long = compiled
        st, metrics = compiled(st, batch)  # warm (first dispatch pays setup)
        jax.block_until_ready(metrics)
        best = float("inf")
        for _w in range(int(windows)):
            t0 = time.perf_counter()
            st, metrics = compiled(st, batch)
            jax.block_until_ready(metrics)
            best = min(best, time.perf_counter() - t0)
        times[length] = best
    per_step_s = (times[long_] - times[short]) / (long_ - short)
    measurement = {
        "step_ms": round(per_step_s * 1e3, 4),
        "chain_steps": int(chain_steps),
        "windows": int(windows),
    }
    if categories:
        # Category capture (perf_gate idiom): trace ONE extra long window
        # AFTER the timed ones (the trace gates nothing it measures) and
        # attach StepProfile category fractions; degrade gracefully.
        import shutil
        import sys
        import tempfile

        from distributed_training_pytorch_tpu import profiling as profiling_lib

        prof_dir = tempfile.mkdtemp(prefix="autotune_prof_")
        try:
            with profiling_lib.trace(prof_dir):
                st, metrics = compiled_long(st, batch)
                jax.block_until_ready(metrics)
            prof = profiling_lib.analyze_trace(prof_dir, steps=long_)
            measurement["categories"] = {
                k: round(v, 4) for k, v in prof.categories.items() if v
            }
        except (ValueError, FileNotFoundError, OSError, RuntimeError) as e:
            print(f"autotune: category capture failed ({e}) — this "
                  "candidate's delta will be unattributed", file=sys.stderr)
        finally:
            shutil.rmtree(prof_dir, ignore_errors=True)
    return measurement, st


def rank_candidates(
    baseline: dict,
    results: list[dict],
    *,
    metric: str = "step_ms",
    rel_margin: float = FLAT_REL_TOL,
) -> dict:
    """Rank measured candidates against the baseline; refuse unsound ones.

    ``baseline``/``results[i]`` are ``{"name", "knobs", "measurement"}``
    dicts where measurement carries ``metric`` (+ optionally ``categories``
    and ``provenance`` from ``telemetry.provenance.provenance_fields``).

    * **Refusal** (the PR 14 rule, sweep-adapted): provenance CONFIG keys
      that differ from the baseline and are NOT declared in the candidate's
      ``knobs`` make the comparison meaningless — the candidate lands in
      ``refused`` with the offending keys named, never in the ranking.
    * **Ranking**: accepted candidates sort by ``metric`` ascending; each
      carries its delta vs baseline and the per-category attribution rows
      (``profiling.diff.attribute_entry_delta`` — None when either side
      lacks categories).
    * **Keep rule**: the best candidate becomes ``winner`` only if it beats
      the baseline by more than ``rel_margin`` (default: the flat-streak
      band ``FLAT_REL_TOL``); otherwise ``kept`` is False and the baseline
      config stands — a sub-noise "win" is reverted, not shipped.
    """
    from distributed_training_pytorch_tpu.profiling import diff as diff_lib
    from distributed_training_pytorch_tpu.telemetry import provenance

    base_meas = baseline["measurement"]
    base_val = float(base_meas[metric])
    base_prov = base_meas.get("provenance") or {}
    ranked: list[dict] = []
    refused: list[dict] = []
    for r in results:
        meas = r["measurement"]
        swept = set(r.get("knobs") or {})
        prov = meas.get("provenance") or {}
        undeclared = [
            k for k in provenance.differing_keys(base_prov, prov)
            if k not in swept
        ]
        if undeclared:
            refused.append({
                "name": r["name"],
                "differing_keys": undeclared,
                "reason": "provenance facets differ on keys the candidate "
                          "did not declare as swept — comparison refused "
                          "(PR 14 rule)",
            })
            continue
        rows = diff_lib.attribute_entry_delta(base_meas, meas, metric=metric)
        ranked.append({
            "name": r["name"],
            "knobs": dict(r.get("knobs") or {}),
            "note": r.get("note", ""),
            "measurement": meas,
            "delta_ms": round(float(meas[metric]) - base_val, 4),
            "attribution": [row.to_dict() for row in rows] if rows else None,
            "attribution_text": (
                diff_lib.describe_rows(rows) if rows else ""
            ),
        })
    ranked.sort(key=lambda e: float(e["measurement"][metric]))
    kept = bool(ranked) and (
        float(ranked[0]["measurement"][metric]) < base_val * (1.0 - rel_margin)
    )
    return {
        "schema": 1,
        "metric": metric,
        "rel_margin": rel_margin,
        "baseline": baseline,
        "ranked": ranked,
        "refused": refused,
        "kept": kept,
        "winner": ranked[0] if kept else None,
    }


def emit_tuned(path: str, report: dict) -> dict:
    """Write the sweep report as the committed ``TUNED.json`` artifact.

    The file IS the evidence: baseline + every ranked candidate with its
    delta and per-category attribution + every refusal with the offending
    provenance keys + the keep/revert verdict. Reviewing the TUNED.json
    diff reviews the perf claim (same ritual as PERF_BASELINE.json).

    Rank 0 owns the file (utils/logger convention) — a multi-host sweep
    measures everywhere but writes once. Imported lazily: this module must
    stay importable before jax init (``tuned_defaults`` runs pre-backend).
    """
    import jax

    if jax.process_index() == 0:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=False)
            f.write("\n")
    return report


def load_tuned(path: str = DEFAULT_TUNED_PATH) -> dict | None:
    """Load a committed TUNED.json; None when absent/unreadable (the
    autotuner-off default must never make an entry fail to start)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return None


def tuned_defaults(path: str | None = None, env=None) -> dict:
    """The entry-side opt-in: the kept winner's knobs, or ``{}``.

    Gated on ``TUNED=1`` in ``env`` (default ``os.environ``) — unset/other
    means ``{}``, so the autotuner being off changes nothing anywhere.
    Entries consult the returned knobs as DEFAULTS only; the explicit env
    knobs (CHAIN_STEPS, PALLAS, ...) still win, preserving the env-at-entry
    convention.

    Side effect, by design: when the kept winner carries ``xla_flags`` and
    the process has no ``XLA_FLAGS`` yet, they are installed into ``env``
    — this is how a per-compile sweep win is applied process-wide in
    production, so call this BEFORE the first jax use (the examples do, at
    import-knob time). An explicit ``XLA_FLAGS`` is never overridden, and
    the install is SKIPPED when ``JAX_PLATFORMS`` explicitly pins a
    non-TPU backend: the committed winners are ``--xla_tpu_*`` flags, and
    XLA aborts the whole process (``parse_flags_from_env`` is fatal, not a
    warning) on flags the compiled-in backend doesn't know — a CPU smoke
    of a TUNED entry must degrade to untuned, not die at import.
    """
    env = os.environ if env is None else env
    if env.get("TUNED") != "1":
        return {}
    data = load_tuned(path or DEFAULT_TUNED_PATH)
    if not data or not data.get("kept") or not data.get("winner"):
        return {}
    knobs = dict(data["winner"].get("knobs") or {})
    flags = knobs.get("xla_flags")
    platforms = (env.get("JAX_PLATFORMS") or "").strip().lower()
    tpu_possible = not platforms or any(
        p in ("tpu", "axon") for p in platforms.replace(",", " ").split()
    )
    if flags and not env.get("XLA_FLAGS") and tpu_possible:
        env["XLA_FLAGS"] = flags
    return knobs
