"""Backpressure-aware retry client for the serving fleet (ISSUE 20).

The degradation contract on the server side is *typed* refusal: an
overloaded tenant gets 429, a draining/re-planning replica gets 503, and
both carry a ``Retry-After`` header derived from live queue depth and
the drain deadline (``InferenceServer.retry_after_s``). This module is
the caller's half of that contract — the part that makes a degraded
window *survivable* instead of merely observable:

* **Honor ``Retry-After`` first.** When the server says when to come
  back, believe it: the delay for that attempt is
  ``max(Retry-After, backoff)``. The server computed it from queue depth
  and the remaining drain deadline; the client's exponential guess is a
  fallback, not an override.
* **Jittered exponential backoff** otherwise: ``base * 2**attempt``
  capped at ``max_delay_s``, multiplied by a uniform jitter in
  ``[1 - jitter, 1 + jitter]`` so a fleet of callers released by the
  same drain does not re-stampede the replica in lockstep (the classic
  thundering-herd failure the drain itself just avoided).
* **Bounded attempts, typed give-up.** After ``max_attempts`` the
  client raises :class:`RetriesExhausted` carrying every attempt's
  status/delay — a caller distinguishes "the fleet is degraded, here is
  the evidence" from a silent hang or an untyped stack trace.
* **Only retry what retrying can fix**: 429/503 (admission pushback)
  and transport-level connection errors (replica mid-restart). A 400 is
  the caller's bug and a 500 is the server's; both surface immediately.

Pure stdlib (urllib), injectable transport/sleep/rng/clock — the policy
is unit-testable without a socket (tests/test_serving_drain.py), and the
soak's actuation leg drives the real HTTP path with it, proving zero
*failed* requests across a drain/re-plan window even though individual
attempts inside it were shed with 503.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request

__all__ = ["RetriesExhausted", "RetryClient"]

# HTTP statuses that mean "come back later", not "you are wrong": the
# bounded-queue 429 and the draining/re-planning 503. Everything else is
# terminal for the request as submitted.
RETRYABLE_STATUSES = (429, 503)


class RetriesExhausted(RuntimeError):
    """Every bounded attempt was refused: the typed give-up. ``attempts``
    is a list of ``{"status", "retry_after_s", "slept_s", "error"}`` dicts,
    one per try in order — the evidence a caller (or the soak's assertion)
    needs to tell a correctly-degraded fleet from a broken one."""

    def __init__(self, url: str, attempts: "list[dict]"):
        statuses = [a.get("status") or a.get("error") for a in attempts]
        super().__init__(
            f"{len(attempts)} attempts to {url} all refused ({statuses}): "
            "giving up"
        )
        self.url = url
        self.attempts = attempts


class RetryClient:
    """POST JSON with jittered-exponential retry honoring ``Retry-After``
    (see module doc). ``transport(url, body_bytes, timeout) -> (status,
    body_bytes, headers_dict)`` is injectable for tests; the default uses
    urllib and maps ``HTTPError`` into the same triple so 4xx/5xx are
    *data* here, not exceptions."""

    def __init__(
        self,
        *,
        max_attempts: int = 6,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        jitter: float = 0.25,
        timeout_s: float = 10.0,
        transport=None,
        sleep=time.sleep,
        rng: "random.Random | None" = None,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if not (0.0 <= jitter < 1.0):
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.timeout_s = float(timeout_s)
        self._transport = transport or self._urllib_transport
        self._sleep = sleep
        self._rng = rng or random.Random()
        # -- counters (soak legs assert on these) --------------------------
        self.requests = 0  # logical requests (post_json calls)
        self.attempts_total = 0  # physical HTTP attempts
        self.retries = 0  # attempts beyond the first
        self.gave_up = 0  # RetriesExhausted raised

    # -- transport ---------------------------------------------------------

    def _urllib_transport(self, url: str, body: bytes, timeout: float):
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            # 4xx/5xx carry a body and headers we need (Retry-After!):
            # surface them as data, same shape as a 200.
            return e.code, e.read(), dict(e.headers)

    # -- policy ------------------------------------------------------------

    def _backoff_s(self, attempt: int) -> float:
        raw = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        if self.jitter:
            raw *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, raw)

    @staticmethod
    def _retry_after(headers: dict) -> "float | None":
        for key, value in (headers or {}).items():
            if str(key).lower() == "retry-after":
                try:
                    return max(0.0, float(value))
                except (TypeError, ValueError):
                    return None
        return None

    # -- the call ----------------------------------------------------------

    def post_json(self, url: str, payload: dict) -> "tuple[int, dict]":
        """POST ``payload`` as JSON, retrying 429/503 and connection
        errors per the module-doc policy. Returns ``(status, body_dict)``
        for any non-retryable outcome (including 400/500 — the caller
        decides what those mean); raises :class:`RetriesExhausted` when
        every bounded attempt was refused."""
        body = json.dumps(payload).encode()
        self.requests += 1
        attempts: "list[dict]" = []
        for attempt in range(self.max_attempts):
            self.attempts_total += 1
            if attempt:
                self.retries += 1
            try:
                status, raw, headers = self._transport(url, body, self.timeout_s)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # Replica mid-restart / socket refused: retryable, but
                # there is no server-supplied Retry-After to honor.
                delay = self._backoff_s(attempt)
                attempts.append(
                    {
                        "status": None,
                        "error": f"{type(e).__name__}: {e}",
                        "retry_after_s": None,
                        "slept_s": round(delay, 4),
                    }
                )
                if attempt + 1 < self.max_attempts:
                    self._sleep(delay)
                continue
            if status not in RETRYABLE_STATUSES:
                try:
                    parsed = json.loads(raw.decode() or "{}")
                except (ValueError, UnicodeDecodeError):
                    parsed = {"raw": raw.decode(errors="replace")}
                return status, parsed
            retry_after = self._retry_after(headers)
            delay = self._backoff_s(attempt)
            if retry_after is not None:
                delay = max(delay, retry_after)
            attempts.append(
                {
                    "status": int(status),
                    "error": None,
                    "retry_after_s": retry_after,
                    "slept_s": round(delay, 4),
                }
            )
            if attempt + 1 < self.max_attempts:
                self._sleep(delay)
        self.gave_up += 1
        raise RetriesExhausted(url, attempts)
