"""Rank-0 HTTP inference server with checkpoint hot-swap (ISSUE 18 c).

The PR 15 exporter pattern, grown one route: a stdlib
``ThreadingHTTPServer`` on daemon threads serving

* ``POST /predict`` — admit a request into the continuous micro-batcher
  (:mod:`.batcher`), block the handler thread until its batch completes,
  answer with the outputs and the params version they were computed on.
  A full tenant queue answers HTTP 429 with the typed overload facts —
  never queues unboundedly, never hangs (the zero-capacity soak leg).
* ``GET /status``  — one JSON snapshot: p50/p99 latency, QPS and
  QPS/chip, params version, swap/reject/batch counters, SLO verdict.
* ``GET /metrics`` — the same snapshot as Prometheus text
  (``telemetry.exporter.prometheus_text``, ``tpu_serve_`` prefix).

Hot-swap under load (the PR 5 snapshot->commit manifest, read side): a
watcher thread polls the checkpoint directory; when the served name
(``best`` preferred, newest-valid fallback) commits a new manifest, it
restores ``params_only`` OFF the request path and installs the new tree
via ``InferEngine.swap_params`` — one atomic reference flip. In-flight
batches finish on the params they started with; no request ever stalls
on a swap (docs/serving.md "Hot-swap state machine").

Observability rides the existing flight recorder: the server claims an
attempt id and emits ``serve_start`` / ``request_batch`` (a ~1 Hz
summary pulse that doubles as the liveness heartbeat) / ``hot_swap`` /
``admission_reject`` (debounced per tenant) into
``<run_dir>/telemetry/events.jsonl`` — so ``RunMonitor``, the fleet
table, and the fleet controller supervise a server exactly like a
trainer (docs/observability.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from distributed_training_pytorch_tpu.serving.batcher import (
    MicroBatcher,
    OverloadRejected,
)
from distributed_training_pytorch_tpu.telemetry.events import (
    EventLog,
    _jsonable,
    claim_attempt,
    resolve_events_path,
)
from distributed_training_pytorch_tpu.telemetry.exporter import prometheus_text

__all__ = ["InferenceServer", "LatencyWindow"]


class LatencyWindow:
    """Trailing-window latency/throughput accounting: completion times and
    per-request latencies over the last ``window_s`` seconds. p50/p99 by
    nearest-rank quantile on the live window — small (seconds of traffic),
    so sorting per snapshot is cheap and exact."""

    def __init__(self, window_s: float = 30.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._done: list = []  # (t_done, latency_ms), trimmed on insert

    def add(self, t_done: float, latency_ms: float) -> None:
        with self._lock:
            self._done.append((t_done, latency_ms))
            cutoff = t_done - self.window_s
            if self._done and self._done[0][0] < cutoff:
                self._done = [d for d in self._done if d[0] >= cutoff]

    def snapshot(self, now: "float | None" = None) -> dict:
        now = self._clock() if now is None else now
        cutoff = now - self.window_s
        with self._lock:
            live = [d for d in self._done if d[0] >= cutoff]
        if not live:
            return {"qps": 0.0, "p50_ms": None, "p99_ms": None, "window_n": 0}
        lat = sorted(d[1] for d in live)
        span = min(self.window_s, max(now - live[0][0], 1e-6))

        def q(p: float) -> float:
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        return {
            "qps": round(len(live) / span, 2),
            "p50_ms": round(q(0.50), 3),
            "p99_ms": round(q(0.99), 3),
            "window_n": len(lat),
        }


class InferenceServer:
    """One serving replica (see module doc).

    ``engine`` is a params-loaded :class:`~.engine.InferEngine`;
    ``manager``/``target_state`` (optional) arm the hot-swap watcher —
    ``serve_name`` picks what it follows (default: ``"best"`` when that
    name exists, else the newest valid checkpoint). ``slo_p99_ms`` arms
    the SLO verdict surfaced on ``/status`` and the ``request_batch``
    pulse (the monitor's server exit-code contract). ``port=0`` binds
    ephemeral; read it back from :attr:`port`.
    """

    def __init__(
        self,
        engine,
        *,
        batcher: "MicroBatcher | None" = None,
        port: int = 0,
        host: str = "127.0.0.1",
        run_dir: "str | None" = None,
        manager=None,
        target_state=None,
        serve_name: "str | None" = None,
        swap_poll_s: float = 0.5,
        slo_p99_ms: "float | None" = None,
        window_s: float = 30.0,
        pulse_every_s: float = 1.0,
        request_timeout_s: float = 30.0,
        input_dtype: str = "float32",
        process_index: "int | None" = None,
        clock=time.monotonic,
        log=print,
    ):
        self.engine = engine
        # The default batcher shares the server's clock: Request.arrival and
        # the latency math in _dispatch_loop must read the same timebase.
        self.batcher = batcher if batcher is not None else MicroBatcher(
            buckets=engine.buckets, clock=clock
        )
        self._requested_port = int(port)
        self.host = host
        self.run_dir = run_dir
        self.manager = manager
        self.target_state = target_state
        self.serve_name = serve_name
        self.swap_poll_s = float(swap_poll_s)
        self.slo_p99_ms = slo_p99_ms
        self.pulse_every_s = float(pulse_every_s)
        self.request_timeout_s = float(request_timeout_s)
        self.input_dtype = np.dtype(input_dtype)
        self._clock = clock
        self._log = log
        self.window = LatencyWindow(window_s, clock=clock)
        self.port: "int | None" = None
        self.enabled = False
        self.attempt: "int | None" = None
        self._stop = threading.Event()
        self._threads: list = []
        self._server: "ThreadingHTTPServer | None" = None
        self._started = 0.0
        self.requests_total = 0
        self._swap_identity = None
        self._reject_debounce: dict = {}  # tenant -> (last_emit_t, count_since)
        self._pulse_state = {"t": 0.0, "requests": 0, "batches": 0}
        self._lock = threading.Lock()
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self.process_index = int(process_index)
        self.events = None
        if run_dir is not None and self.process_index == 0:
            self.events = EventLog(resolve_events_path(run_dir), process_index=0)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceServer":
        """Bind, start dispatch + swap + HTTP threads, emit ``serve_start``.
        Only rank 0 serves (the exporter/EventLog ownership rule); other
        ranks no-op with ``enabled=False``."""
        if self.process_index != 0:
            return self
        if self.run_dir is not None:
            self.attempt = claim_attempt(self.run_dir)
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — silence stdlib logging
                pass

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                route = self.path.split("?", 1)[0].rstrip("/") or "/status"
                snapshot = server.snapshot()
                if route in ("/status", "/"):
                    self._respond(
                        200, "application/json", json.dumps(_jsonable(snapshot)) + "\n"
                    )
                elif route == "/metrics":
                    self._respond(
                        200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        prometheus_text(
                            {k: v for k, v in snapshot.items() if v is not None},
                            prefix="tpu_serve",
                        ),
                    )
                else:
                    self._respond(404, "text/plain", "try /predict, /status or /metrics\n")

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                route = self.path.split("?", 1)[0].rstrip("/")
                if route != "/predict":
                    self._respond(404, "text/plain", "POST /predict only\n")
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    tenant = str(body.get("tenant", "default"))
                    inputs = np.asarray(body["inputs"], dtype=server.input_dtype)
                except (KeyError, TypeError, ValueError) as e:
                    self._respond(
                        400, "application/json",
                        json.dumps({"error": "bad_request", "detail": str(e)}) + "\n",
                    )
                    return
                code, payload = server.handle_predict(tenant, inputs)
                self._respond(code, "application/json", payload)

            def _respond(self, code: int, ctype: str, body: str):
                try:
                    payload = body.encode("utf-8")
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except OSError:
                    pass  # client went away mid-response: its problem

        try:
            self._server = ThreadingHTTPServer(
                (self.host, self._requested_port), _Handler
            )
        except OSError as e:
            # The exporter's taken-port policy: serving disabled with one
            # warning — an observability/port clash must be diagnosable,
            # not a crash loop.
            self._log(
                f"inference server disabled — could not bind "
                f"{self.host}:{self._requested_port} ({e})"
            )
            return self
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._started = self._clock()
        self._pulse_state["t"] = self._started
        for name, fn in (
            ("serve-dispatch", self._dispatch_loop),
            ("serve-http", self._server.serve_forever),
        ):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.manager is not None and self.target_state is not None:
            # A preloaded engine already serving the candidate checkpoint
            # (version "<name>@e<epoch>" from restore_params) adopts its
            # identity up front: the watcher's first poll must not redo a
            # full restore and emit a spurious hot_swap for params the
            # engine was just loaded with.
            if self._swap_identity is None and self.engine.params_version is not None:
                try:
                    cand = self._swap_candidate()
                except Exception:  # noqa: BLE001 — racing commit: watcher decides
                    cand = None
                if cand is not None and str(self.engine.params_version).startswith(
                    f"{cand[0]}@"
                ):
                    self._swap_identity = cand
            t = threading.Thread(
                target=self._swap_loop, name="serve-hotswap", daemon=True
            )
            t.start()
            self._threads.append(t)
        self.enabled = True
        if self.events is not None:
            self.events.emit(
                "serve_start",
                attempt=self.attempt,
                port=self.port,
                buckets=list(self.engine.buckets),
                max_delay_s=self.batcher.max_delay_s,
                max_queue_depth=self.batcher.max_queue_depth,
                slo_p99_ms=self.slo_p99_ms,
                params_version=self.engine.params_version,
                mesh_axes={str(k): int(v) for k, v in self.engine.mesh.shape.items()},
            )
        return self

    def close(self) -> None:
        """Graceful stop: drain the queue, stop threads, emit ``run_end``
        (the monitor's finished marker). Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        if self.events is not None and self.enabled:
            self.events.emit("run_end", attempt=self.attempt, kind="serve")
            self.events.close()
        self.enabled = False

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request path ------------------------------------------------------

    def handle_predict(self, tenant: str, inputs: np.ndarray) -> "tuple[int, str]":
        """Admit -> wait -> answer. Returns (HTTP code, JSON body). The
        response body is a pure function of (inputs, served params): no
        timestamps or latencies in it, so equal params produce equal bytes
        across a hot-swap boundary (the soak's bit-identity leg)."""
        if inputs.ndim == 0 or inputs.shape[0] == 0:
            return 400, json.dumps({"error": "bad_request", "detail": "empty inputs"}) + "\n"
        try:
            # One request row per payload so the batcher's fairness applies
            # per row — admitted atomically, so a 429 on a multi-row POST
            # never leaves already-queued orphan rows dispatching behind it.
            reqs = self.batcher.submit_many(tenant, list(inputs))
        except OverloadRejected as e:
            self._note_reject(e)
            return 429, json.dumps(
                {
                    "error": "overload",
                    "tenant": e.tenant,
                    "depth": e.depth,
                    "bound": e.bound,
                }
            ) + "\n"
        deadline = self._clock() + self.request_timeout_s
        for req in reqs:
            if not req.wait(max(0.0, deadline - self._clock())):
                return 504, json.dumps({"error": "timeout"}) + "\n"
            if req.error is not None:
                return 500, json.dumps({"error": "inference_failed", "detail": req.error}) + "\n"
        return 200, json.dumps(
            {
                "outputs": [np.asarray(r.result).tolist() for r in reqs],
                "params_version": reqs[-1].params_version,
            }
        ) + "\n"

    def _note_reject(self, e: OverloadRejected) -> None:
        """``admission_reject`` events, debounced to one per tenant per
        second (a saturating tenant must not flood its own flight
        recorder); the per-tenant counter in /status stays exact."""
        if self.events is None:
            return
        now = self._clock()
        # Handler threads race here: the (last_emit_t, count) read-modify-
        # write must be atomic or debounced counts drop rejects.
        with self._lock:
            last_t, pent = self._reject_debounce.get(e.tenant, (0.0, 0))
            pent += 1
            emit = now - last_t >= 1.0
            self._reject_debounce[e.tenant] = (now, 0) if emit else (last_t, pent)
        if emit:
            self.events.emit(
                "admission_reject",
                attempt=self.attempt,
                tenant=e.tenant,
                depth=e.depth,
                bound=e.bound,
                rejects=pent,
                rejected_total=int(sum(self.batcher.rejected.values())),
            )

    # -- dispatch loop -----------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.next_batch()
            if batch is None:
                self._maybe_pulse()
                # Sleep to the earliest of: the oldest request's flush
                # deadline, the next pulse, or a 2 ms poll tick.
                now = self._clock()
                dl = self.batcher.next_deadline()
                bound = 0.002 if dl is None else max(0.0, min(dl - now, 0.002))
                self._stop.wait(bound)
                continue
            # Per-request validation cannot rule out one batch mixing row
            # shapes (two tenants posting different feature lengths), so
            # group by row signature and run each group on its own: the
            # stack can never throw outside a try and kill this thread, and
            # well-shaped rows never fail for a neighbor's bad shape.
            groups: dict = {}
            for req in batch.requests:
                row = np.asarray(req.payload)
                groups.setdefault((row.shape, str(row.dtype)), []).append(req)
            n_done = 0
            for reqs in groups.values():
                try:
                    payloads = np.stack([np.asarray(r.payload) for r in reqs])
                    out, version = self.engine.predict(payloads)
                except Exception as e:  # noqa: BLE001 — answered as 500s, server survives
                    for req in reqs:
                        req.error = f"{type(e).__name__}: {e}"
                        req.done.set()
                    self._log(f"inference batch failed: {type(e).__name__}: {e}")
                    continue
                t_out = self._clock()
                for i, req in enumerate(reqs):
                    req.result = out[i]
                    req.params_version = version
                    req.completed = t_out
                    self.window.add(t_out, (t_out - req.arrival) * 1e3)
                    req.done.set()
                n_done += len(reqs)
            with self._lock:
                self.requests_total += n_done
                self._pulse_state["requests"] += n_done
                self._pulse_state["batches"] += 1
            self._maybe_pulse()
        # Drain on shutdown: flush whatever is queued so no handler thread
        # is left blocked on a request that will never run.
        batch = self.batcher.next_batch(drain=True)
        while batch is not None:
            for req in batch.requests:
                req.error = "server shutting down"
                req.done.set()
            batch = self.batcher.next_batch(drain=True)

    def _maybe_pulse(self) -> None:
        """The ~1 Hz ``request_batch`` summary record: throughput/latency
        since the last pulse plus the trailing-window quantiles. Emitted
        even when idle — it doubles as the server's liveness heartbeat for
        the monitor (an idle healthy replica must not read as dead)."""
        if self.events is None:
            return
        now = self._clock()
        with self._lock:
            if now - self._pulse_state["t"] < self.pulse_every_s:
                return
            since = now - self._pulse_state["t"]
            requests, batches = (
                self._pulse_state["requests"],
                self._pulse_state["batches"],
            )
            self._pulse_state.update(t=now, requests=0, batches=0)
        win = self.window.snapshot(now)
        self.events.emit(
            "request_batch",
            attempt=self.attempt,
            requests=requests,
            batches=batches,
            interval_s=round(since, 3),
            qps=win["qps"],
            p50_ms=win["p50_ms"],
            p99_ms=win["p99_ms"],
            slo_p99_ms=self.slo_p99_ms,
            slo_ok=self._slo_ok(win),
            params_version=self.engine.params_version,
            rejected_total=int(sum(self.batcher.rejected.values())),
        )

    def _slo_ok(self, win: dict) -> "bool | None":
        if self.slo_p99_ms is None:
            return None
        if win["p99_ms"] is None:
            return True  # no traffic in the window: nothing breached
        return bool(win["p99_ms"] <= self.slo_p99_ms)

    # -- hot-swap watcher --------------------------------------------------

    def _swap_candidate(self) -> "tuple[str, float] | None":
        """(name, manifest mtime) of the checkpoint this replica should be
        serving: the pinned ``serve_name`` when set, else ``best`` when it
        exists, else the newest valid. The mtime is the commit identity —
        the atomic rename that publishes a checkpoint also refreshes it."""
        from distributed_training_pytorch_tpu.checkpoint.manager import MANIFEST_NAME

        name = self.serve_name
        if name is None:
            name = "best" if self.manager.exists("best") else (
                self.manager.latest_valid_name()
            )
        if name is None or not self.manager.exists(name):
            return None
        try:
            mtime = os.path.getmtime(os.path.join(self.manager.path(name), MANIFEST_NAME))
        except OSError:
            return None
        return (name, mtime)

    def _swap_loop(self) -> None:
        while not self._stop.wait(self.swap_poll_s):
            try:
                cand = self._swap_candidate()
            except Exception:  # noqa: BLE001 — a racing commit retries next poll
                continue
            if cand is None or cand == self._swap_identity:
                continue
            before = self.engine.params_version
            t0 = self._clock()
            try:
                version = self.engine.restore_params(
                    self.manager, self.target_state, name=cand[0]
                )
            except Exception as e:  # noqa: BLE001 — serve the old params; retry next poll
                self._log(f"hot-swap restore failed (serving old params): {e}")
                continue
            with self._lock:
                self._swap_identity = cand
            if self.events is not None:
                self.events.emit(
                    "hot_swap",
                    attempt=self.attempt,
                    checkpoint=cand[0],
                    from_version=before,
                    to_version=version,
                    swap_ms=round((self._clock() - t0) * 1e3, 2),
                    swaps=self.engine.swap_count,
                    pending_requests=self.batcher.pending(),
                )

    # -- status ------------------------------------------------------------

    def snapshot(self) -> dict:
        now = self._clock()
        win = self.window.snapshot(now)
        stats = self.batcher.stats()
        import jax

        n_chips = jax.device_count()
        return {
            "kind": "server",
            "port": self.port,
            "attempt": self.attempt,
            "uptime_s": round(now - self._started, 1) if self._started else 0.0,
            "params_version": self.engine.params_version,
            "swaps": self.engine.swap_count,
            "requests_total": self.requests_total,
            "pending": stats["pending"],
            "rejected": stats["rejected"],
            "rejected_total": stats["rejected_total"],
            "batches": stats["batches"],
            "pad_frac": round(stats["pad_frac"], 4),
            "flushes": stats["flushes"],
            "qps": win["qps"],
            "qps_per_chip": round(win["qps"] / n_chips, 3),
            "p50_ms": win["p50_ms"],
            "p99_ms": win["p99_ms"],
            "slo_p99_ms": self.slo_p99_ms,
            "slo_ok": self._slo_ok(win),
            "trace_counts": dict(self.engine.trace_counts),
            "buckets": list(self.engine.buckets),
        }
