"""Rank-0 HTTP inference server with checkpoint hot-swap (ISSUE 18 c).

The PR 15 exporter pattern, grown one route: a stdlib
``ThreadingHTTPServer`` on daemon threads serving

* ``POST /predict`` — admit a request into the continuous micro-batcher
  (:mod:`.batcher`), block the handler thread until its batch completes,
  answer with the outputs and the params version they were computed on.
  A full tenant queue answers HTTP 429 with the typed overload facts —
  never queues unboundedly, never hangs (the zero-capacity soak leg).
* ``GET /status``  — one JSON snapshot: p50/p99 latency, QPS and
  QPS/chip, params version, swap/reject/batch counters, SLO verdict.
* ``GET /metrics`` — the same snapshot as Prometheus text
  (``telemetry.exporter.prometheus_text``, ``tpu_serve_`` prefix).

Hot-swap under load (the PR 5 snapshot->commit manifest, read side): a
watcher thread polls the checkpoint directory; when the served name
(``best`` preferred, newest-valid fallback) commits a new manifest, it
restores ``params_only`` OFF the request path and installs the new tree
via ``InferEngine.swap_params`` — one atomic reference flip. In-flight
batches finish on the params they started with; no request ever stalls
on a swap (docs/serving.md "Hot-swap state machine").

Graceful drain + live re-plan (ISSUE 20 tentpole): the server owns a
three-state admission machine — ``serving -> draining -> replanning ->
serving``. :meth:`InferenceServer.drain` stops admitting (new requests
get a typed 503 with a ``Retry-After`` derived from live queue depth),
flushes in-flight micro-batches to completion under a bounded deadline,
and answers whatever is still *queued* past the deadline with the same
typed 503 — never a hang, never a dropped row. :meth:`drain_and_replan`
then rebuilds the ``InferEngine``'s executables on a new device set
through the elastic solver (``parallel.elastic.replan``), warms the
buckets, and resumes — response bytes for identical params are
bit-identical across the re-plan. The checkpoint-watch thread is gated
behind the same state machine: a commit landing mid-drain cannot flip
params while the engine is being rebuilt. ``POST /admin/offer`` /
``POST /admin/replan`` are the fleet controller's handshake transport
(offer -> accept/decline -> actuate -> confirm); a replica under SLO
pressure declines.

Observability rides the existing flight recorder: the server claims an
attempt id and emits ``serve_start`` / ``request_batch`` (a ~1 Hz
summary pulse that doubles as the liveness heartbeat — it keeps firing
mid-drain/re-plan, stamped with the admission state, so the monitor
never reads a draining replica as dead) / ``hot_swap`` /
``admission_reject`` (debounced per tenant, carrying the Retry-After it
answered with) / ``drain_start`` / ``replan_done`` /
``offer_accept`` / ``offer_decline`` into
``<run_dir>/telemetry/events.jsonl`` — so ``RunMonitor``, the fleet
table, and the fleet controller supervise a server exactly like a
trainer (docs/observability.md).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from distributed_training_pytorch_tpu.serving.batcher import (
    MicroBatcher,
    OverloadRejected,
)
from distributed_training_pytorch_tpu.telemetry.events import (
    EventLog,
    _jsonable,
    claim_attempt,
    resolve_events_path,
)
from distributed_training_pytorch_tpu.telemetry.exporter import prometheus_text

__all__ = ["InferenceServer", "LatencyWindow"]


class LatencyWindow:
    """Trailing-window latency/throughput accounting: completion times and
    per-request latencies over the last ``window_s`` seconds. p50/p99 by
    nearest-rank quantile on the live window — small (seconds of traffic),
    so sorting per snapshot is cheap and exact."""

    def __init__(self, window_s: float = 30.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._done: list = []  # (t_done, latency_ms), trimmed on insert

    def add(self, t_done: float, latency_ms: float) -> None:
        with self._lock:
            self._done.append((t_done, latency_ms))
            cutoff = t_done - self.window_s
            if self._done and self._done[0][0] < cutoff:
                self._done = [d for d in self._done if d[0] >= cutoff]

    def snapshot(self, now: "float | None" = None) -> dict:
        now = self._clock() if now is None else now
        cutoff = now - self.window_s
        with self._lock:
            live = [d for d in self._done if d[0] >= cutoff]
        if not live:
            return {"qps": 0.0, "p50_ms": None, "p99_ms": None, "window_n": 0}
        lat = sorted(d[1] for d in live)
        span = min(self.window_s, max(now - live[0][0], 1e-6))

        def q(p: float) -> float:
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        return {
            "qps": round(len(live) / span, 2),
            "p50_ms": round(q(0.50), 3),
            "p99_ms": round(q(0.99), 3),
            "window_n": len(lat),
        }


class InferenceServer:
    """One serving replica (see module doc).

    ``engine`` is a params-loaded :class:`~.engine.InferEngine`;
    ``manager``/``target_state`` (optional) arm the hot-swap watcher —
    ``serve_name`` picks what it follows (default: ``"best"`` when that
    name exists, else the newest valid checkpoint). ``slo_p99_ms`` arms
    the SLO verdict surfaced on ``/status`` and the ``request_batch``
    pulse (the monitor's server exit-code contract). ``port=0`` binds
    ephemeral; read it back from :attr:`port`.
    """

    def __init__(
        self,
        engine,
        *,
        batcher: "MicroBatcher | None" = None,
        port: int = 0,
        host: str = "127.0.0.1",
        run_dir: "str | None" = None,
        manager=None,
        target_state=None,
        serve_name: "str | None" = None,
        swap_poll_s: float = 0.5,
        slo_p99_ms: "float | None" = None,
        window_s: float = 30.0,
        pulse_every_s: float = 1.0,
        request_timeout_s: float = 30.0,
        input_dtype: str = "float32",
        process_index: "int | None" = None,
        clock=time.monotonic,
        log=print,
    ):
        self.engine = engine
        # The default batcher shares the server's clock: Request.arrival and
        # the latency math in _dispatch_loop must read the same timebase.
        self.batcher = batcher if batcher is not None else MicroBatcher(
            buckets=engine.buckets, clock=clock
        )
        self._requested_port = int(port)
        self.host = host
        self.run_dir = run_dir
        self.manager = manager
        self.target_state = target_state
        self.serve_name = serve_name
        self.swap_poll_s = float(swap_poll_s)
        self.slo_p99_ms = slo_p99_ms
        self.pulse_every_s = float(pulse_every_s)
        self.request_timeout_s = float(request_timeout_s)
        self.input_dtype = np.dtype(input_dtype)
        self._clock = clock
        self._log = log
        self.window = LatencyWindow(window_s, clock=clock)
        self.port: "int | None" = None
        self.enabled = False
        self.attempt: "int | None" = None
        self._stop = threading.Event()
        self._threads: list = []
        self._server: "ThreadingHTTPServer | None" = None
        self._started = 0.0
        self.requests_total = 0
        # Admission state machine (ISSUE 20): "serving" admits; "draining"
        # refuses admission while in-flight batches flush under a bounded
        # deadline; "replanning" refuses while the engine rebuilds on a new
        # device set. Transitions happen under _lock; readers take the GIL
        # snapshot (a stale read costs one extra 503, never a torn state).
        self.state = "serving"
        self._drain_deadline: "float | None" = None
        self._inflight = 0  # micro-batches currently executing in dispatch
        self.drain_count = 0
        self.shed_total = 0  # requests answered a drain-window 503
        self._warm_row = None  # first served row: the post-replan warmup sig
        self._swap_identity = None
        self._reject_debounce: dict = {}  # tenant -> (last_emit_t, count_since)
        self._pulse_state = {"t": 0.0, "requests": 0, "batches": 0}
        self._lock = threading.Lock()
        if process_index is None:
            import jax

            process_index = jax.process_index()
        self.process_index = int(process_index)
        self.events = None
        if run_dir is not None and self.process_index == 0:
            self.events = EventLog(resolve_events_path(run_dir), process_index=0)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "InferenceServer":
        """Bind, start dispatch + swap + HTTP threads, emit ``serve_start``.
        Only rank 0 serves (the exporter/EventLog ownership rule); other
        ranks no-op with ``enabled=False``."""
        if self.process_index != 0:
            return self
        if self.run_dir is not None:
            self.attempt = claim_attempt(self.run_dir)
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: D102 — silence stdlib logging
                pass

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                route = self.path.split("?", 1)[0].rstrip("/") or "/status"
                snapshot = server.snapshot()
                if route in ("/status", "/"):
                    self._respond(
                        200, "application/json", json.dumps(_jsonable(snapshot)) + "\n"
                    )
                elif route == "/metrics":
                    self._respond(
                        200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        prometheus_text(
                            {k: v for k, v in snapshot.items() if v is not None},
                            prefix="tpu_serve",
                        ),
                    )
                else:
                    self._respond(404, "text/plain", "try /predict, /status or /metrics\n")

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
                route = self.path.split("?", 1)[0].rstrip("/")
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (TypeError, ValueError) as e:
                    self._respond(
                        400, "application/json",
                        json.dumps({"error": "bad_request", "detail": str(e)}) + "\n",
                    )
                    return
                if route == "/predict":
                    try:
                        tenant = str(body.get("tenant", "default"))
                        inputs = np.asarray(
                            body["inputs"], dtype=server.input_dtype
                        )
                    except (KeyError, TypeError, ValueError) as e:
                        self._respond(
                            400, "application/json",
                            json.dumps(
                                {"error": "bad_request", "detail": str(e)}
                            ) + "\n",
                        )
                        return
                    code, payload, headers = server.handle_predict(tenant, inputs)
                elif route == "/admin/offer":
                    code, payload, headers = server.handle_offer(body)
                elif route == "/admin/replan":
                    code, payload, headers = server.handle_replan(body)
                else:
                    self._respond(
                        404, "text/plain",
                        "POST /predict, /admin/offer or /admin/replan\n",
                    )
                    return
                self._respond(code, "application/json", payload, headers)

            def _respond(self, code: int, ctype: str, body: str, headers=None):
                try:
                    payload = body.encode("utf-8")
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(payload)))
                    for key, value in (headers or {}).items():
                        self.send_header(key, str(value))
                    self.end_headers()
                    self.wfile.write(payload)
                except OSError:
                    pass  # client went away mid-response: its problem

        try:
            self._server = ThreadingHTTPServer(
                (self.host, self._requested_port), _Handler
            )
        except OSError as e:
            # The exporter's taken-port policy: serving disabled with one
            # warning — an observability/port clash must be diagnosable,
            # not a crash loop.
            self._log(
                f"inference server disabled — could not bind "
                f"{self.host}:{self._requested_port} ({e})"
            )
            return self
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self._started = self._clock()
        self._pulse_state["t"] = self._started
        for name, fn in (
            ("serve-dispatch", self._dispatch_loop),
            ("serve-http", self._server.serve_forever),
        ):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.manager is not None and self.target_state is not None:
            # A preloaded engine already serving the candidate checkpoint
            # (version "<name>@e<epoch>" from restore_params) adopts its
            # identity up front: the watcher's first poll must not redo a
            # full restore and emit a spurious hot_swap for params the
            # engine was just loaded with.
            if self._swap_identity is None and self.engine.params_version is not None:
                try:
                    cand = self._swap_candidate()
                except Exception:  # noqa: BLE001 — racing commit: watcher decides
                    cand = None
                if cand is not None and str(self.engine.params_version).startswith(
                    f"{cand[0]}@"
                ):
                    self._swap_identity = cand
            t = threading.Thread(
                target=self._swap_loop, name="serve-hotswap", daemon=True
            )
            t.start()
            self._threads.append(t)
        self.enabled = True
        if self.events is not None:
            self.events.emit(
                "serve_start",
                attempt=self.attempt,
                port=self.port,
                buckets=list(self.engine.buckets),
                max_delay_s=self.batcher.max_delay_s,
                max_queue_depth=self.batcher.max_queue_depth,
                slo_p99_ms=self.slo_p99_ms,
                params_version=self.engine.params_version,
                mesh_axes={str(k): int(v) for k, v in self.engine.mesh.shape.items()},
            )
        return self

    def close(self) -> None:
        """Graceful stop: drain the queue, stop threads, emit ``run_end``
        (the monitor's finished marker). Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        if self.events is not None and self.enabled:
            self.events.emit("run_end", attempt=self.attempt, kind="serve")
            self.events.close()
        self.enabled = False

    def __enter__(self) -> "InferenceServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- request path ------------------------------------------------------

    def retry_after_s(self) -> int:
        """Advisory seconds before a refused caller should retry — the
        ``Retry-After`` header on every 429/503 (ISSUE 20 satellite 1),
        derived from live queue depth: pending rows amortized over the
        largest bucket estimate the batches ahead, each costing about one
        admission window plus the trailing p50 service time. Mid-drain the
        remaining drain budget floors the answer — retrying into a replica
        that is still flushing or recompiling cannot succeed sooner."""
        depth = self.batcher.pending()
        win = self.window.snapshot()
        per_batch_s = self.batcher.max_delay_s + ((win["p50_ms"] or 0.0) / 1e3)
        est = ((depth // self.batcher.buckets[-1]) + 1) * per_batch_s
        dl = self._drain_deadline
        if self.state != "serving" and dl is not None:
            est = max(est, dl - self._clock())
        return max(1, math.ceil(est))

    def handle_predict(
        self, tenant: str, inputs: np.ndarray
    ) -> "tuple[int, str, dict | None]":
        """Admit -> wait -> answer. Returns (HTTP code, JSON body, extra
        headers or None). The response body is a pure function of (inputs,
        served params): no timestamps or latencies in it, so equal params
        produce equal bytes across a hot-swap or re-plan boundary (the
        soak's bit-identity legs). While the server drains or re-plans,
        admission answers a typed 503 with Retry-After — the degraded-mode
        shed contract callers' retry loops key off."""
        if inputs.ndim == 0 or inputs.shape[0] == 0:
            return 400, json.dumps(
                {"error": "bad_request", "detail": "empty inputs"}
            ) + "\n", None
        state = self.state
        if state != "serving":
            ra = self.retry_after_s()
            with self._lock:
                self.shed_total += 1
            self._note_reject(
                tenant,
                depth=self.batcher.pending(),
                bound=self.batcher.max_queue_depth,
                reason=state,
                retry_after_s=ra,
            )
            return 503, json.dumps(
                {"error": "draining", "state": state, "retry_after_s": ra}
            ) + "\n", {"Retry-After": str(ra)}
        try:
            # One request row per payload so the batcher's fairness applies
            # per row — admitted atomically, so a 429 on a multi-row POST
            # never leaves already-queued orphan rows dispatching behind it.
            reqs = self.batcher.submit_many(tenant, list(inputs))
        except OverloadRejected as e:
            ra = self.retry_after_s()
            self._note_reject(
                e.tenant,
                depth=e.depth,
                bound=e.bound,
                reason="overload",
                retry_after_s=ra,
            )
            return 429, json.dumps(
                {
                    "error": "overload",
                    "tenant": e.tenant,
                    "depth": e.depth,
                    "bound": e.bound,
                }
            ) + "\n", {"Retry-After": str(ra)}
        deadline = self._clock() + self.request_timeout_s
        for req in reqs:
            if not req.wait(max(0.0, deadline - self._clock())):
                return 504, json.dumps({"error": "timeout"}) + "\n", None
            if req.error is not None:
                if req.error_code == 503:
                    # Shed by a drain deadline: typed, timed, retryable.
                    ra = self.retry_after_s()
                    return 503, json.dumps(
                        {
                            "error": "draining",
                            "state": self.state,
                            "detail": req.error,
                            "retry_after_s": ra,
                        }
                    ) + "\n", {"Retry-After": str(ra)}
                return 500, json.dumps(
                    {"error": "inference_failed", "detail": req.error}
                ) + "\n", None
        return 200, json.dumps(
            {
                "outputs": [np.asarray(r.result).tolist() for r in reqs],
                "params_version": reqs[-1].params_version,
            }
        ) + "\n", None

    def _note_reject(
        self, tenant: str, *, depth: int, bound: int, reason: str,
        retry_after_s: int,
    ) -> None:
        """``admission_reject`` events, debounced to one per tenant per
        second (a saturating tenant must not flood its own flight
        recorder); the per-tenant counter in /status stays exact. Covers
        both refusal flavors — ``reason="overload"`` (429, bounded queue
        full) and ``reason="draining"/"replanning"`` (503, admission
        closed) — and records the Retry-After the caller was answered
        with (satellite 1)."""
        if self.events is None:
            return
        now = self._clock()
        # Handler threads race here: the (last_emit_t, count) read-modify-
        # write must be atomic or debounced counts drop rejects.
        with self._lock:
            last_t, pent = self._reject_debounce.get(tenant, (0.0, 0))
            pent += 1
            emit = now - last_t >= 1.0
            self._reject_debounce[tenant] = (now, 0) if emit else (last_t, pent)
        if emit:
            self.events.emit(
                "admission_reject",
                attempt=self.attempt,
                tenant=tenant,
                depth=depth,
                bound=bound,
                reason=reason,
                retry_after_s=int(retry_after_s),
                rejects=pent,
                rejected_total=int(sum(self.batcher.rejected.values())),
            )

    # -- drain + live re-plan (ISSUE 20 tentpole) --------------------------

    def drain(self, *, deadline_s: float = 10.0) -> dict:
        """Stop admitting and flush in-flight micro-batches under a bounded
        deadline. New requests get the typed 503 the moment the state
        flips; queued requests keep dispatching (the loop flushes partial
        batches immediately while draining); whatever is STILL queued when
        the deadline passes is answered the same typed 503 — shed, never
        dropped, never hung. A batch already executing at the deadline
        always completes (its rows are answered 200: in-flight rows are
        never dropped). Leaves the server in state ``"replanning"`` with
        dispatch quiesced — callers resume via :meth:`drain_and_replan`
        (the normal path) or :meth:`resume` (drain-only callers, tests)."""
        deadline_s = float(deadline_s)
        with self._lock:
            if self.state != "serving":
                raise RuntimeError(
                    f"drain requested while already {self.state}"
                )
            self.state = "draining"
            self._drain_deadline = self._clock() + deadline_s
            self.drain_count += 1
        t0 = self._clock()
        deadline = self._drain_deadline
        pending0 = self.batcher.pending()
        if self.events is not None:
            self.events.emit(
                "drain_start",
                attempt=self.attempt,
                deadline_s=deadline_s,
                pending=pending0,
                params_version=self.engine.params_version,
            )
        # Bounded flush: the dispatch loop drains the queue; wait for it.
        while self._clock() < deadline:
            if self.batcher.pending() == 0 and self._inflight == 0:
                break
            self._stop.wait(0.001)
        with self._lock:
            self.state = "replanning"  # dispatch stops taking batches
        # A batch the loop already took keeps running — let it finish.
        while self._inflight > 0 and not self._stop.is_set():
            self._stop.wait(0.001)
        # Past-deadline: everything still queued gets the typed 503.
        shed = 0
        batch = self.batcher.next_batch(drain=True)
        while batch is not None:
            for req in batch.requests:
                req.error = "drain deadline exceeded; replica re-planning"
                req.error_code = 503
                req.done.set()
                shed += 1
            batch = self.batcher.next_batch(drain=True)
        with self._lock:
            self.shed_total += shed
        return {
            "pending_at_drain": pending0,
            "shed": shed,
            "drain_ms": round((self._clock() - t0) * 1e3, 2),
        }

    def resume(self) -> None:
        """Re-open admission (state back to ``"serving"``). Idempotent."""
        with self._lock:
            self.state = "serving"
            self._drain_deadline = None

    def drain_and_replan(
        self, device_ids, *, deadline_s: float = 10.0
    ) -> dict:
        """The actuated-offer path: solve the elastic plan for the new
        device set, drain under ``deadline_s``, rebuild the engine's
        executables on the new mesh, warm the buckets, resume, and emit
        ``replan_done``. Feasibility is checked BEFORE admission stops —
        an infeasible target (unknown device id, a bucket not dividing
        the new batch-shard extent) raises and leaves the replica serving
        its old plan untouched, which is what the controller's revert
        path relies on. On a post-drain failure the replica still resumes
        on the old plan (the engine mutates nothing until its own
        validation passes)."""
        import jax

        from distributed_training_pytorch_tpu.parallel import elastic
        from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib

        ids = sorted({int(d) for d in device_ids})
        if not ids:
            raise ValueError("replan target names no devices")
        by_id = {int(d.id): d for d in jax.devices()}
        unknown = [d for d in ids if d not in by_id]
        if unknown:
            raise ValueError(
                f"replan target names unknown device id(s) {unknown} "
                f"(backend has {sorted(by_id)})"
            )
        old_axes = {
            str(k): int(v) for k, v in self.engine.mesh.shape.items()
        }
        plan = elastic.replan(old_axes, len(ids))
        new_extent = max(
            1,
            int(plan.new_axes.get("data", 1))
            * int(plan.new_axes.get("fsdp", 1)),
        )
        bad = [b for b in self.engine.buckets if b % new_extent]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the re-planned batch-shard "
                f"extent {new_extent} (plan {plan.new_axes}): refusing "
                "before any admission stops"
            )
        new_mesh = plan.mesh_config.build([by_id[d] for d in ids])
        t0 = self._clock()
        summary = self.drain(deadline_s=deadline_s)
        try:
            self.engine.replan_onto(new_mesh)
            warm = self._warm_row
            if warm is not None:
                # Recompile before taking traffic: the first post-replan
                # request must not pay the compile.
                self.engine.warmup(warm)
        finally:
            # Success or failure, admission re-opens: a failed replan left
            # the engine on its old (validated-untouched) plan.
            self.resume()
        summary.update(
            state=self.state,
            device_ids=ids,
            mesh_axes={
                str(k): int(v) for k, v in self.engine.mesh.shape.items()
            },
            params_version=self.engine.params_version,
            replan_ms=round((self._clock() - t0) * 1e3, 2),
            plan_reason=plan.reason,
        )
        if self.events is not None:
            self.events.emit(
                "replan_done",
                attempt=self.attempt,
                from_mesh=old_axes,
                to_mesh=summary["mesh_axes"],
                device_ids=ids,
                shed=summary["shed"],
                replan_ms=summary["replan_ms"],
                params_version=self.engine.params_version,
                replans=self.engine.replan_count,
                plan_reason=plan.reason,
            )
        return summary

    # -- the offer handshake, replica side ---------------------------------

    def handle_offer(self, body: dict) -> "tuple[int, str, dict | None]":
        """The replica's half of the chip-offer handshake: the fleet
        controller POSTs a freed chip; this replica accepts unless it is
        already mid-drain or under SLO pressure — a replica breaching its
        p99 must not take a drain + recompile window on top of the
        breach. The decision is emitted (``offer_accept`` /
        ``offer_decline``) so the handshake audits from the flight
        recorder alone; accepting commits to nothing — the controller
        actuates separately via ``POST /admin/replan``."""
        chip = body.get("chip")
        if not isinstance(chip, (int, float)):
            return 400, json.dumps(
                {"error": "bad_request", "detail": "no chip in offer"}
            ) + "\n", None
        chip = int(chip)
        win = self.window.snapshot()
        slo_ok = self._slo_ok(win)
        state = self.state
        if state != "serving":
            decision, reason = "decline", f"replica is {state}"
        elif slo_ok is False:
            decision, reason = "decline", (
                f"under SLO pressure: p99 {win['p99_ms']}ms > "
                f"{self.slo_p99_ms}ms"
            )
        else:
            decision, reason = "accept", "healthy and serving"
        if self.events is not None:
            self.events.emit(
                "offer_accept" if decision == "accept" else "offer_decline",
                attempt=self.attempt,
                chip=chip,
                reason=reason,
                state=state,
                slo_ok=slo_ok,
                p99_ms=win["p99_ms"],
                pending=self.batcher.pending(),
            )
        return 200, json.dumps(
            {"decision": decision, "chip": chip, "reason": reason}
        ) + "\n", None

    def handle_replan(self, body: dict) -> "tuple[int, str, dict | None]":
        """``POST /admin/replan``: actuate a drain + re-plan onto
        ``body["device_ids"]``. 409 while a drain is already in progress;
        400 (old plan untouched, still serving) when the target is
        infeasible."""
        device_ids = body.get("device_ids")
        if not isinstance(device_ids, (list, tuple)) or not device_ids:
            return 400, json.dumps(
                {"error": "bad_request", "detail": "device_ids required"}
            ) + "\n", None
        deadline_s = float(body.get("deadline_s", 10.0))
        if self.state != "serving":
            ra = self.retry_after_s()
            return 409, json.dumps(
                {"error": "busy", "state": self.state, "retry_after_s": ra}
            ) + "\n", {"Retry-After": str(ra)}
        try:
            summary = self.drain_and_replan(
                device_ids, deadline_s=deadline_s
            )
        except Exception as e:  # noqa: BLE001 — typed refusal, old plan serving
            return 400, json.dumps(
                {
                    "error": "replan_failed",
                    "detail": f"{type(e).__name__}: {e}",
                    "state": self.state,
                }
            ) + "\n", None
        return 200, json.dumps(_jsonable(summary)) + "\n", None

    # -- dispatch loop -----------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            state = self.state
            if state == "replanning":
                # Quiesced: the drain owns the queue now. Keep pulsing so
                # the monitor sees a live (never dead) replica mid-replan.
                self._maybe_pulse()
                self._stop.wait(0.002)
                continue
            # While draining, flush partial batches immediately — waiting
            # out max_delay_s inside a bounded drain window wastes it.
            batch = self.batcher.next_batch(drain=(state == "draining"))
            if batch is None:
                self._maybe_pulse()
                # Sleep to the earliest of: the oldest request's flush
                # deadline, the next pulse, or a 2 ms poll tick.
                now = self._clock()
                dl = self.batcher.next_deadline()
                bound = 0.002 if dl is None else max(0.0, min(dl - now, 0.002))
                self._stop.wait(bound)
                continue
            with self._lock:
                self._inflight += 1
            # Per-request validation cannot rule out one batch mixing row
            # shapes (two tenants posting different feature lengths), so
            # group by row signature and run each group on its own: the
            # stack can never throw outside a try and kill this thread, and
            # well-shaped rows never fail for a neighbor's bad shape.
            groups: dict = {}
            for req in batch.requests:
                row = np.asarray(req.payload)
                groups.setdefault((row.shape, str(row.dtype)), []).append(req)
                if self._warm_row is None:
                    # Remembered for the post-replan warmup: the traffic's
                    # own row signature is what the rebuilt executables
                    # must be compiled for.
                    self._warm_row = row
            n_done = 0
            try:
                for reqs in groups.values():
                    try:
                        payloads = np.stack(
                            [np.asarray(r.payload) for r in reqs]
                        )
                        out, version = self.engine.predict(payloads)
                    except Exception as e:  # noqa: BLE001 — answered as 500s, server survives
                        for req in reqs:
                            req.error = f"{type(e).__name__}: {e}"
                            req.done.set()
                        self._log(
                            f"inference batch failed: {type(e).__name__}: {e}"
                        )
                        continue
                    t_out = self._clock()
                    for i, req in enumerate(reqs):
                        req.result = out[i]
                        req.params_version = version
                        req.completed = t_out
                        self.window.add(t_out, (t_out - req.arrival) * 1e3)
                        req.done.set()
                    n_done += len(reqs)
            finally:
                with self._lock:
                    self._inflight -= 1
                    self.requests_total += n_done
                    self._pulse_state["requests"] += n_done
                    self._pulse_state["batches"] += 1
            self._maybe_pulse()
        # Drain on shutdown: flush whatever is queued so no handler thread
        # is left blocked on a request that will never run.
        batch = self.batcher.next_batch(drain=True)
        while batch is not None:
            for req in batch.requests:
                req.error = "server shutting down"
                req.done.set()
            batch = self.batcher.next_batch(drain=True)

    def _maybe_pulse(self) -> None:
        """The ~1 Hz ``request_batch`` summary record: throughput/latency
        since the last pulse plus the trailing-window quantiles. Emitted
        even when idle — it doubles as the server's liveness heartbeat for
        the monitor (an idle healthy replica must not read as dead)."""
        if self.events is None:
            return
        now = self._clock()
        with self._lock:
            if now - self._pulse_state["t"] < self.pulse_every_s:
                return
            since = now - self._pulse_state["t"]
            requests, batches = (
                self._pulse_state["requests"],
                self._pulse_state["batches"],
            )
            self._pulse_state.update(t=now, requests=0, batches=0)
        win = self.window.snapshot(now)
        mesh_chips = max(1, int(self.engine.mesh.devices.size))
        self.events.emit(
            "request_batch",
            attempt=self.attempt,
            requests=requests,
            batches=batches,
            interval_s=round(since, 3),
            qps=win["qps"],
            # Per-MESH-chip, not per-backend-chip: the denominator is the
            # replica's own device set, so absorbing an offered chip moves
            # this number — the handshake's A/B metric (ISSUE 20).
            qps_per_chip=round(win["qps"] / mesh_chips, 3),
            mesh_chips=mesh_chips,
            p50_ms=win["p50_ms"],
            p99_ms=win["p99_ms"],
            slo_p99_ms=self.slo_p99_ms,
            slo_ok=self._slo_ok(win),
            # The admission state rides the liveness pulse: a draining or
            # re-planning replica keeps heartbeating, visibly mid-drain —
            # the monitor must never read it as dead.
            state=self.state,
            params_version=self.engine.params_version,
            rejected_total=int(sum(self.batcher.rejected.values())),
            shed_total=self.shed_total,
        )

    def _slo_ok(self, win: dict) -> "bool | None":
        if self.slo_p99_ms is None:
            return None
        if win["p99_ms"] is None:
            return True  # no traffic in the window: nothing breached
        return bool(win["p99_ms"] <= self.slo_p99_ms)

    # -- hot-swap watcher --------------------------------------------------

    def _swap_candidate(self) -> "tuple[str, float] | None":
        """(name, manifest mtime) of the checkpoint this replica should be
        serving: the pinned ``serve_name`` when set, else ``best`` when it
        exists, else the newest valid. The mtime is the commit identity —
        the atomic rename that publishes a checkpoint also refreshes it."""
        from distributed_training_pytorch_tpu.checkpoint.manager import MANIFEST_NAME

        name = self.serve_name
        if name is None:
            name = "best" if self.manager.exists("best") else (
                self.manager.latest_valid_name()
            )
        if name is None or not self.manager.exists(name):
            return None
        try:
            mtime = os.path.getmtime(os.path.join(self.manager.path(name), MANIFEST_NAME))
        except OSError:
            return None
        return (name, mtime)

    def _swap_loop(self) -> None:
        while not self._stop.wait(self.swap_poll_s):
            if self.state != "serving":
                # Satellite 2 (ISSUE 20): a checkpoint commit landing
                # mid-drain/re-plan must not flip params while the engine
                # is being rebuilt — the watcher is gated behind the same
                # state machine the drain owns, and simply re-arms on the
                # first poll after the server resumes (the candidate is
                # re-derived from disk, so nothing is missed).
                continue
            try:
                cand = self._swap_candidate()
            except Exception:  # noqa: BLE001 — a racing commit retries next poll
                continue
            if cand is None or cand == self._swap_identity:
                continue
            before = self.engine.params_version
            t0 = self._clock()
            try:
                version = self.engine.restore_params(
                    self.manager, self.target_state, name=cand[0]
                )
            except Exception as e:  # noqa: BLE001 — serve the old params; retry next poll
                self._log(f"hot-swap restore failed (serving old params): {e}")
                continue
            with self._lock:
                self._swap_identity = cand
            if self.events is not None:
                self.events.emit(
                    "hot_swap",
                    attempt=self.attempt,
                    checkpoint=cand[0],
                    from_version=before,
                    to_version=version,
                    swap_ms=round((self._clock() - t0) * 1e3, 2),
                    swaps=self.engine.swap_count,
                    pending_requests=self.batcher.pending(),
                )

    # -- status ------------------------------------------------------------

    def snapshot(self) -> dict:
        now = self._clock()
        win = self.window.snapshot(now)
        stats = self.batcher.stats()
        # Per-MESH-chip: the replica's own device set, so the handshake's
        # before/after probe sees the absorbed chip in the denominator.
        n_chips = max(1, int(self.engine.mesh.devices.size))
        return {
            "kind": "server",
            "port": self.port,
            "attempt": self.attempt,
            "state": self.state,
            "uptime_s": round(now - self._started, 1) if self._started else 0.0,
            "params_version": self.engine.params_version,
            "swaps": self.engine.swap_count,
            "replans": self.engine.replan_count,
            "chips": n_chips,
            "device_ids": sorted(
                int(d.id) for d in self.engine.mesh.devices.flat
            ),
            "drains": self.drain_count,
            "shed_total": self.shed_total,
            "requests_total": self.requests_total,
            "pending": stats["pending"],
            "rejected": stats["rejected"],
            "rejected_total": stats["rejected_total"],
            "batches": stats["batches"],
            "pad_frac": round(stats["pad_frac"], 4),
            "flushes": stats["flushes"],
            "qps": win["qps"],
            "qps_per_chip": round(win["qps"] / n_chips, 3),
            "p50_ms": win["p50_ms"],
            "p99_ms": win["p99_ms"],
            "slo_p99_ms": self.slo_p99_ms,
            "slo_ok": self._slo_ok(win),
            "trace_counts": dict(self.engine.trace_counts),
            "buckets": list(self.engine.buckets),
        }
