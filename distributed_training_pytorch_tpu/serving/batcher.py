"""Continuous micro-batching request admission (ISSUE 18 tentpole b).

The serving analog of the training loader's batching policy, inverted:
training pulls fixed-size batches from an unbounded corpus; serving is
handed an unpredictable request stream and must *form* batches under a
latency budget. The policy here is the standard continuous-batching
compromise, stated precisely so the tests can pin its boundaries:

* **Bucketized batch sizes.** Batches flush at one of a fixed ascending
  tuple of sizes (``buckets``), padding the tail — so the engine compiles
  ``len(buckets)`` executables per input signature instead of one per
  observed batch size (the ``TrainEngine`` per-shape cache contract,
  shared by :class:`~.engine.InferEngine`).
* **Admit-until-bucket-deadline.** A request waits at most
  ``max_delay_s`` in the queue before its batch flushes: the queue keeps
  admitting until either the largest bucket fills (flush immediately —
  more waiting cannot improve occupancy) or the *oldest* pending
  request's deadline arrives (flush whatever is queued, padded to the
  smallest covering bucket). Latency cost of batching is therefore
  bounded by ``max_delay_s`` exactly, not amortized.
* **Per-tenant fair admission.** Pending requests queue per tenant
  (FIFO within a tenant); a flushing batch drafts round-robin *across*
  tenants, so a greedy tenant with a deep queue cannot starve a quiet
  one out of a bucket.
* **Bounded depth, typed rejection.** Each tenant holds at most
  ``max_queue_depth`` undispatched requests; the next submit raises
  :class:`OverloadRejected` (typed, counted per tenant) instead of
  queueing unboundedly. ``max_queue_depth=0`` refuses every request
  immediately — a zero-capacity config must refuse, not hang
  (test-enforced, and a soak leg).

Pure Python + threading primitives: no jax import, injectable clock,
unit-testable without devices (tests/test_serving.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import Counter, OrderedDict, deque
from typing import Any

__all__ = [
    "MicroBatch",
    "MicroBatcher",
    "OverloadRejected",
    "Request",
    "pick_bucket",
]


class OverloadRejected(RuntimeError):
    """A tenant's bounded queue is full (or capacity is zero): the request
    was refused at admission, never queued. Carries the facts a caller
    needs to shape the HTTP 429 / backpressure decision."""

    def __init__(self, tenant: str, depth: int, bound: int):
        super().__init__(
            f"tenant {tenant!r} queue at depth {depth} >= bound {bound}: "
            "request rejected at admission"
        )
        self.tenant = tenant
        self.depth = depth
        self.bound = bound


def pick_bucket(n: int, buckets: tuple) -> int:
    """The smallest bucket >= ``n`` (boundary-exact: ``n`` equal to a
    bucket size picks that bucket, one over picks the next). Raises
    ``ValueError`` when ``n`` exceeds the largest bucket — the caller
    split the work wrong, and padding cannot fix it."""
    if n <= 0:
        raise ValueError(f"batch of {n} requests has no bucket")
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} requests exceed the largest bucket {buckets[-1]}")


@dataclasses.dataclass
class Request:
    """One admitted request: payload plus its completion rendezvous.
    The dispatcher fills ``result``/``error`` and sets ``done``; the
    submitting thread blocks in :meth:`wait`."""

    id: int
    tenant: str
    payload: Any
    arrival: float  # batcher-clock admission time (latency accounting)
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Any = None
    error: "str | None" = None
    # HTTP status an errored request maps to: 500 for an inference failure,
    # 503 when a drain's bounded deadline shed the request before dispatch
    # (typed, with Retry-After — never a hang or a dropped row; ISSUE 20).
    error_code: int = 500
    params_version: "str | None" = None
    completed: float = 0.0

    def wait(self, timeout: "float | None" = None) -> bool:
        return self.done.wait(timeout)


@dataclasses.dataclass(frozen=True)
class MicroBatch:
    """One flushed batch: the drafted requests, the bucket they pad to,
    and why the flush fired (``"full"`` — largest bucket occupied;
    ``"deadline"`` — oldest request's wait hit ``max_delay_s``;
    ``"drain"`` — caller-forced shutdown flush)."""

    requests: tuple
    bucket: int
    flushed_by: str

    @property
    def pad(self) -> int:
        return self.bucket - len(self.requests)

    def payloads(self) -> list:
        return [r.payload for r in self.requests]


class MicroBatcher:
    """The admission queue + flush policy (see module doc). Thread-safe:
    ``submit`` is called from request threads, ``next_batch`` from the
    dispatch loop. ``clock`` is injectable so the deadline policy is
    testable without sleeping."""

    def __init__(
        self,
        *,
        buckets: tuple = (1, 2, 4, 8),
        max_delay_s: float = 0.02,
        max_queue_depth: int = 64,
        clock=time.monotonic,
    ):
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        if len(set(buckets)) != len(buckets):
            raise ValueError(f"duplicate buckets: {buckets!r}")
        self.buckets = buckets
        self.max_delay_s = float(max_delay_s)
        self.max_queue_depth = int(max_queue_depth)
        self._clock = clock
        self._lock = threading.Lock()
        # Tenant order is admission order of first appearance; the draft
        # rotation walks it round-robin starting past the last tenant
        # drafted first, so no tenant owns the front of every batch.
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._rr_next = 0  # rotation offset into the tenant order
        self._ids = itertools.count()
        # -- counters (exported via stats(); the server's /status) --------
        self.submitted = 0
        self.rejected: Counter = Counter()  # per tenant
        self.batches = 0
        self.padded_slots = 0
        self.flushes: Counter = Counter()  # by flushed_by reason

    # -- admission ---------------------------------------------------------

    def submit(self, tenant: str, payload: Any, *, now: "float | None" = None) -> Request:
        """Admit one request, or raise :class:`OverloadRejected` when the
        tenant's bounded queue is full. Never blocks."""
        return self.submit_many(tenant, (payload,), now=now)[0]

    def submit_many(
        self, tenant: str, payloads, *, now: "float | None" = None
    ) -> "list[Request]":
        """Admit every payload or none: capacity for the whole list is
        reserved atomically under the lock. A multi-row request that would
        overflow the tenant's bound is rejected wholesale — rejection can
        never leave already-admitted orphan rows behind it, still queued
        and burning compute after the caller was told 429."""
        payloads = list(payloads)
        if not payloads:
            return []
        now = self._clock() if now is None else now
        with self._lock:
            q = self._queues.get(tenant)
            depth = len(q) if q is not None else 0
            if depth + len(payloads) > self.max_queue_depth:
                self.rejected[tenant] += 1
                raise OverloadRejected(tenant, depth, self.max_queue_depth)
            if q is None:
                q = deque()
                self._queues[tenant] = q
            reqs = [
                Request(id=next(self._ids), tenant=tenant, payload=p, arrival=now)
                for p in payloads
            ]
            q.extend(reqs)
            self.submitted += len(reqs)
            return reqs

    def pending(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def next_deadline(self) -> "float | None":
        """Clock time at which the oldest pending request forces a flush
        (the dispatch loop's sleep bound), or None when idle."""
        with self._lock:
            oldest = self._oldest_arrival()
        return None if oldest is None else oldest + self.max_delay_s

    def _oldest_arrival(self) -> "float | None":
        arrivals = [q[0].arrival for q in self._queues.values() if q]
        return min(arrivals) if arrivals else None

    # -- the flush policy --------------------------------------------------

    def next_batch(
        self, *, now: "float | None" = None, drain: bool = False
    ) -> "MicroBatch | None":
        """One dispatch-loop poll: a flushed :class:`MicroBatch` when the
        policy says go, else None (keep admitting). ``drain=True`` flushes
        whatever is pending regardless of deadline (shutdown path)."""
        now = self._clock() if now is None else now
        with self._lock:
            total = sum(len(q) for q in self._queues.values())
            if total == 0:
                return None
            full = total >= self.buckets[-1]
            oldest = self._oldest_arrival()
            deadline_hit = oldest is not None and (now - oldest) >= self.max_delay_s
            if not (full or deadline_hit or drain):
                return None
            take = min(total, self.buckets[-1])
            bucket = pick_bucket(take, self.buckets)
            drafted = self._draft(take)
            reason = "full" if full else ("deadline" if deadline_hit else "drain")
            self.batches += 1
            self.padded_slots += bucket - len(drafted)
            self.flushes[reason] += 1
            return MicroBatch(requests=tuple(drafted), bucket=bucket, flushed_by=reason)

    def _draft(self, take: int) -> list:
        """Draft ``take`` requests round-robin across tenant queues (FIFO
        within each): one per tenant per rotation sweep, so bucket slots
        split evenly among whoever is waiting. The rotation start advances
        each batch — no tenant is structurally first."""
        tenants = list(self._queues.keys())
        drafted: list = []
        if tenants:
            start = self._rr_next % len(tenants)
            order = tenants[start:] + tenants[:start]
            self._rr_next += 1
            while len(drafted) < take:
                progressed = False
                for t in order:
                    if len(drafted) >= take:
                        break
                    q = self._queues[t]
                    if q:
                        drafted.append(q.popleft())
                        progressed = True
                if not progressed:
                    break
        # Empty tenant queues are dropped so a long-gone tenant does not
        # hold a rotation slot (and the dict does not grow unboundedly).
        for t in [t for t, q in self._queues.items() if not q]:
            del self._queues[t]
        return drafted

    # -- export ------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            dispatched = self.batches and (
                self.submitted - sum(len(q) for q in self._queues.values())
            )
            return {
                "submitted": self.submitted,
                "rejected": dict(self.rejected),
                "rejected_total": sum(self.rejected.values()),
                "batches": self.batches,
                "padded_slots": self.padded_slots,
                "pad_frac": (
                    self.padded_slots / (self.padded_slots + dispatched)
                    if dispatched
                    else 0.0
                ),
                "flushes": dict(self.flushes),
                "pending": sum(len(q) for q in self._queues.values()),
                "buckets": list(self.buckets),
                "max_delay_s": self.max_delay_s,
                "max_queue_depth": self.max_queue_depth,
            }
