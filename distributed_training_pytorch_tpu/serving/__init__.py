"""Serving subsystem: continuous-batching inference on the training machinery.

ISSUE 18 — the first consumer shape in this repo that is not an epoch
loop. Three layers, each reusing a proven training-side pattern instead
of inventing a serving-only twin:

* :mod:`.batcher` — pure-Python request admission: continuous
  micro-batching with bucketized batch sizes, admit-until-bucket-deadline
  flushing, per-tenant fair admission, and typed overload rejection.
  No jax import; unit-testable without devices.
* :mod:`.engine`  — :class:`~.engine.InferEngine`: forward-only compiled
  executables with a per-bucket-shape cache and ``trace_counts``
  accounting (the ``TrainEngine`` contract), params loaded from the
  async saver's crash-consistent manifest (``restore_latest_valid`` /
  ``best``) and hot-swapped by atomic reference flip.
* :mod:`.server`  — :class:`~.server.InferenceServer`: the rank-0 stdlib
  HTTP server (the PR 15 exporter pattern) exposing ``/predict``,
  ``/status`` and ``/metrics`` (p50/p99 latency, QPS/chip), plus the
  ISSUE 20 drain/re-plan admin surface (``/admin/offer``,
  ``/admin/replan``), emitting the serving event vocabulary
  (``serve_start`` / ``request_batch`` / ``hot_swap`` /
  ``admission_reject`` / ``offer_accept`` / ``offer_decline`` /
  ``drain_start`` / ``replan_done``) into the same JSONL flight
  recorder the fleet monitor and controller already read.
* :mod:`.client`  — :class:`~.client.RetryClient`: the caller's half of
  the backpressure contract (ISSUE 20) — jittered exponential backoff
  honoring ``Retry-After``, bounded attempts, typed give-up
  (:class:`~.client.RetriesExhausted`). Pure stdlib, no jax import.

Import neutrality: importing this package (or any submodule) has no
side effects on the training path — no backend init, no global config
writes; a trainer run with serving imported but unused is bit-exact
with one that never imported it (test-enforced).
"""

from distributed_training_pytorch_tpu.serving.batcher import (  # noqa: F401
    MicroBatch,
    MicroBatcher,
    OverloadRejected,
    Request,
    pick_bucket,
)

# The device-touching layers resolve lazily (PEP 562): the package import
# stays jax-free (the neutrality contract above), but callers still write
# ``from ...serving import InferEngine, InferenceServer``. The client is
# jax-free but lazy too — its urllib import would otherwise drag the
# whole http/email stack into every trainer that imports serving.
_LAZY = {
    "InferEngine": "distributed_training_pytorch_tpu.serving.engine",
    "InferenceServer": "distributed_training_pytorch_tpu.serving.server",
    "LatencyWindow": "distributed_training_pytorch_tpu.serving.server",
    "RetriesExhausted": "distributed_training_pytorch_tpu.serving.client",
    "RetryClient": "distributed_training_pytorch_tpu.serving.client",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "InferEngine",
    "InferenceServer",
    "LatencyWindow",
    "MicroBatch",
    "MicroBatcher",
    "OverloadRejected",
    "Request",
    "RetriesExhausted",
    "RetryClient",
    "pick_bucket",
]
