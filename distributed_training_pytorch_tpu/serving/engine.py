"""Forward-only inference engine on the training machinery (ISSUE 18 a).

``InferEngine`` is ``TrainEngine``'s serving twin, built from the same
parts rather than parallel-evolved copies:

* **Per-bucket executable cache + trace accounting.** One compiled
  forward per (batch bucket, per-row signature) — requests pad up to a
  bucket (``serving.batcher.pick_bucket``) so a live traffic mix hits a
  handful of executables, never a compile per observed batch size.
  ``trace_counts`` bumps once per *trace* inside the jitted body, exactly
  the ``TrainEngine`` contract the retrace-guard CI gate pins — a
  dispatch-path change that silently retraces fails the same way here.
* **Sharded like training.** Params lay out through
  ``parallel.sharding.state_shardings`` with the same rule grammar
  (tensor-parallel rules shard a TP serving mesh; a DP mesh replicates),
  batches shard over the data axis via ``parallel.mesh.batch_sharding``,
  outputs gather replicated. No donation: params are read by every
  request, and serving holds no optimizer state to donate.
* **Params from the async saver's manifest.** ``restore_params`` reads a
  named checkpoint (``best`` / ``last``) or the newest valid one through
  ``CheckpointManager.restore(..., params_only=True)`` /
  ``restore_latest_valid`` — the crash-consistent read side of the PR 5
  snapshot->commit protocol, so a torn in-flight commit can never be
  served.
* **Hot-swap by atomic reference flip.** ``swap_params`` installs a new
  ``(version, params)`` pair with one assignment; ``predict`` reads the
  pair once per call. In-flight batches finish on the params they
  started with — a swap never stalls or tears a request
  (docs/serving.md "Hot-swap state machine").
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_training_pytorch_tpu import compat
from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib
from distributed_training_pytorch_tpu.parallel import sharding as sharding_lib
from distributed_training_pytorch_tpu.serving.batcher import pick_bucket

__all__ = ["InferEngine"]


class InferEngine:
    """Compiled forward-only serving engine (see module doc).

    ``apply_fn(params, inputs) -> outputs`` is the pure forward (e.g.
    ``lambda p, x: model.apply({"params": p}, x)``); ``mesh`` the serving
    mesh from ``parallel.mesh.mesh_config_from_spec`` (TP shards the
    model, DP replicates it and shards the batch). Every bucket must
    divide by the mesh's batch-shard extent — checked up front, because
    the error XLA would raise at dispatch time names neither.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, Any], Any],
        mesh,
        *,
        buckets: tuple = (1, 2, 4, 8),
        sharding_rules: "Sequence | None" = None,
        fsdp_min_size: int = 2**18,
    ):
        self.apply_fn = apply_fn
        self.mesh = mesh
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.sharding_rules = sharding_rules
        self.fsdp_min_size = fsdp_min_size
        extent = mesh_lib.batch_shard_extent(mesh)
        bad = [b for b in self.buckets if b % extent]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the mesh's batch-shard extent "
                f"{extent} (mesh {dict(mesh.shape)}): padded batches could "
                "not lay out over the data axis"
            )
        self._batch_sharding = mesh_lib.batch_sharding(mesh)
        self._replicated = NamedSharding(mesh, P())
        # Current params: ONE tuple (version, device params), swapped by a
        # single reference assignment — the GIL makes the read in predict()
        # and the write in swap_params() each atomic, so there is no torn
        # state a request could observe mid-swap.
        self._current: "tuple[str, Any] | None" = None
        self._params_sharding = None
        self._params_structure = None
        # Executable cache: (bucket, per-row shape, dtype) -> compiled fn.
        # jit itself also caches per shape; this dict keeps the engine's
        # closure-per-signature bookkeeping explicit and countable.
        self._executables: dict = {}
        # Bumped once per TRACE inside the compiled body (TrainEngine's
        # retrace-guard contract): steady-state serving re-traces nothing.
        self.trace_counts: Counter = Counter()
        self.swap_count = 0
        self.replan_count = 0
        self._swap_lock = threading.Lock()  # one restore-and-flip at a time

    # -- params ------------------------------------------------------------

    @property
    def params_version(self) -> "str | None":
        cur = self._current
        return cur[0] if cur is not None else None

    def _ambient_mesh(self):
        # Same reason as TrainEngine._ambient_mesh: in-model bare
        # PartitionSpec constraints resolve against the ambient mesh.
        return compat.set_mesh(self.mesh)

    def _sharding_for(self, params) -> Any:
        leaf_shapes = jax.tree.map(
            lambda x: (tuple(x.shape), str(getattr(x, "dtype", None))), params
        )
        structure = (jax.tree.structure(params), tuple(jax.tree.leaves(leaf_shapes)))
        if self._params_sharding is None:
            self._params_structure = structure
            if self.sharding_rules is None and not any(
                self.mesh.shape.get(a, 1) > 1
                for a in (mesh_lib.FSDP_AXIS, mesh_lib.TENSOR_AXIS)
            ):
                self._params_sharding = self._replicated
            else:
                self._params_sharding = sharding_lib.state_shardings(
                    params,
                    self.mesh,
                    self.sharding_rules or (),
                    fsdp_min_size=self.fsdp_min_size,
                )
        elif structure != self._params_structure:
            raise ValueError(
                "this InferEngine is already bound to a params tree with a "
                "different structure or leaf shapes/dtypes (one engine "
                "serves one model — its executables are compiled against "
                "that layout); build a new engine for the new model."
            )
        return self._params_sharding

    def swap_params(self, params, *, version: str) -> None:
        """Install ``params`` (host or device arrays) as the serving set.
        Lays them out under the engine's sharding, then flips the current
        reference atomically. Compiled executables survive the swap — the
        structure check guarantees the new tree fits them."""
        sharding = self._sharding_for(params)
        placed = jax.device_put(params, sharding)
        # Block until the new params are resident BEFORE flipping, so the
        # first post-swap request never waits on a host->device copy.
        jax.tree.map(
            lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x,
            placed,
        )
        self._current = (str(version), placed)
        self.swap_count += 1

    def restore_params(self, manager, target_state, *, name: "str | None" = None) -> str:
        """Load serving params from the async saver's manifest: the named
        checkpoint (``"best"`` / ``"last"``) when given, else the newest
        valid one (``restore_latest_valid`` — PR 5's torn-commit-proof
        fallback). ``target_state`` is an abstract/concrete TrainState
        template defining the restore layout; ``params_only=True`` keeps
        its optimizer untouched (serving has none worth restoring).
        Returns the installed version string ``<name>@e<epoch>``."""
        with self._swap_lock:
            if name is None:
                state, epoch, used = manager.restore_latest_valid(
                    target_state, params_only=True
                )
            else:
                state, epoch = manager.restore(name, target_state, params_only=True)
                used = name
            version = f"{used}@e{epoch}"
            self.swap_params(state.params, version=version)
            return version

    # -- live re-plan --------------------------------------------------------

    def replan_onto(self, mesh) -> None:
        """Rebind the engine to a re-planned ``mesh`` (ISSUE 20): the live
        re-plan half of the drain handshake. Pulls the served params back
        to host, swaps in the new mesh's batch/params shardings, drops
        every compiled executable (they close over the OLD mesh's
        shardings), then re-places the identical param bytes under the new
        layout — the params version does not change, because the bytes do
        not, so responses for identical inputs are bit-identical across
        the re-plan (batch-axis growth never changes per-row math; a
        model-sharding change is refused upstream by the elastic solver).

        Validation happens BEFORE any state is touched: an infeasible
        target (a bucket not dividing the new batch-shard extent) raises
        ``ValueError`` and leaves the engine serving the old plan — the
        handshake's revert path depends on that. The caller must have
        quiesced dispatch first (the server's drain owns that); the swap
        lock here only excludes a concurrent ``restore_params``."""
        extent = mesh_lib.batch_shard_extent(mesh)
        bad = [b for b in self.buckets if b % extent]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the re-planned mesh's "
                f"batch-shard extent {extent} (mesh {dict(mesh.shape)}): "
                "cannot re-plan this engine onto that device set"
            )
        with self._swap_lock:
            cur = self._current
            host = None
            if cur is not None:
                version, placed = cur
                host = jax.tree.map(
                    lambda x: np.asarray(jax.device_get(x)), placed
                )
            self.mesh = mesh
            self._batch_sharding = mesh_lib.batch_sharding(mesh)
            self._replicated = NamedSharding(mesh, P())
            self._params_sharding = None
            self._params_structure = None
            self._executables = {}
            self.replan_count += 1
            if host is not None:
                sharding = self._sharding_for(host)
                placed = jax.device_put(host, sharding)
                jax.tree.map(
                    lambda x: (
                        x.block_until_ready()
                        if hasattr(x, "block_until_ready")
                        else x
                    ),
                    placed,
                )
                self._current = (version, placed)

    # -- the compiled forward ----------------------------------------------

    def _forward(self, bucket: int, row_sig: tuple):
        key = (bucket, row_sig)
        fn = self._executables.get(key)
        if fn is None:
            params_sharding = self._params_sharding

            def infer_step(params, batch):
                self.trace_counts["infer_step"] += 1
                return self.apply_fn(params, batch)

            # No donate_argnums: params serve every request and batch rows
            # are caller-owned — nothing here is dead after the call.
            fn = jax.jit(
                infer_step,
                in_shardings=(params_sharding, self._batch_sharding),
                out_shardings=self._replicated,
            )
            self._executables[key] = fn
        return fn

    def predict(self, inputs: np.ndarray) -> "tuple[np.ndarray, str]":
        """Run the forward on ``inputs`` (``[n, ...]`` host array): pads
        ``n`` up to the covering bucket (repeating the last row, so padded
        lanes stay numerically tame), dispatches the cached executable,
        slices the pad back off. Returns ``(outputs[:n], params_version)``
        — the version the batch actually ran on, for response stamping
        across hot-swap boundaries."""
        cur = self._current
        if cur is None:
            raise RuntimeError("InferEngine has no params: call restore_params/swap_params first")
        version, params = cur
        inputs = np.asarray(inputs)
        n = int(inputs.shape[0])
        bucket = pick_bucket(n, self.buckets)
        if bucket != n:
            pad = np.broadcast_to(inputs[-1:], (bucket - n,) + inputs.shape[1:])
            inputs = np.concatenate([inputs, pad], axis=0)
        fn = self._forward(bucket, (inputs.shape[1:], str(inputs.dtype)))
        with self._ambient_mesh():
            batch = jax.device_put(inputs, self._batch_sharding)
            out = fn(params, batch)
        return np.asarray(jax.device_get(out))[:n], version

    def warmup(self, example_row: np.ndarray) -> float:
        """Compile every bucket's executable for one row signature before
        taking traffic (first-request latency must not pay a compile).
        Returns the wall seconds spent."""
        t0 = time.perf_counter()
        for b in self.buckets:
            rows = np.broadcast_to(
                np.asarray(example_row)[None], (b,) + np.asarray(example_row).shape
            )
            self.predict(np.ascontiguousarray(rows))
        return time.perf_counter() - t0
