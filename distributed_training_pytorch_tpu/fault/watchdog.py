"""Wall-clock hung-step watchdog.

A training job can stall without dying: a wedged storage mount blocks the
input pipeline, a peer drops out of a collective and everyone else spins in
it, a flaky interconnect link hangs a transfer. Nothing raises — the job
just stops making progress until the scheduler's (much longer) job timeout
reaps it, losing everything since the last checkpoint.

:class:`StepWatchdog` bounds that loss: the step loop ``pat()``\\ s it once
per step from the main thread; a daemon thread checks elapsed time since the
last pat and, past ``timeout`` seconds, invokes ``on_timeout`` — by default
sending this process a SIGTERM, which the ``Trainer``'s preemption handler
already turns into a resumable mid-epoch save at the next safe point. The
hang and the recovery reuse the preemption machinery rather than inventing a
second save path.

The watchdog never acts from signal context and never touches JAX state from
its thread — it only observes timestamps and fires the callback.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional


def _default_on_timeout() -> None:
    os.kill(os.getpid(), signal.SIGTERM)


class StepWatchdog:
    """Fire ``on_timeout`` when no ``pat()`` arrives for ``timeout`` seconds.

    ``on_timeout`` runs on the watchdog thread, at most ``max_fires`` times
    (default once — a hung step does not need a SIGTERM storm). Use as a
    context manager around a step loop::

        with StepWatchdog(timeout=300) as dog:
            for batch in batches:
                step(batch)
                dog.pat()
    """

    def __init__(
        self,
        timeout: float,
        on_timeout: Optional[Callable[[], None]] = None,
        *,
        poll_interval: float | None = None,
        max_fires: int = 1,
        escalation_factor: float = 5.0,
        on_patrol: Optional[Callable[[float], None]] = None,
    ):
        if timeout <= 0:
            raise ValueError(f"timeout must be positive, got {timeout}")
        self.timeout = float(timeout)
        self.on_timeout = on_timeout if on_timeout is not None else _default_on_timeout
        self.poll_interval = (
            poll_interval if poll_interval is not None else min(1.0, self.timeout / 4)
        )
        self.max_fires = max_fires
        # Patrol hook (ISSUE 15): called once per poll iteration on the
        # watchdog thread with the seconds since the last pat — the
        # trainer's liveness heartbeat rides it, so the event log keeps
        # pulsing (with an honest "no progress for N s" figure) while the
        # main thread is stuck inside a step that will not return. Runs
        # outside the lock; exceptions are swallowed (the watchdog must
        # never take the process down, and neither may its passenger).
        self.on_patrol = on_patrol
        # After a fire, the NEXT window is timeout * escalation_factor: the
        # first fire's recovery (SIGTERM -> flag -> break -> save) needs the
        # in-flight step to finish; escalating only declares the thread
        # wedged after that grace multiple passes with no pat.
        self.escalation_factor = float(escalation_factor)
        self.fired = 0
        self._pats = 0
        self._last_pat = time.monotonic()
        # True-progress clock for the patrol hook: pat() alone moves it.
        # _last_pat is re-armed by the fire path (the escalation window
        # must restart after a SIGTERM recovery attempt), so a heartbeat
        # reading _last_pat would claim progress the moment the watchdog
        # fired — reporting a still-hung run as freshly alive.
        self._last_progress = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # pat()/elapsed run on the training thread, _run on the watchdog
        # thread; both touch _last_pat/_pats/fired. CPython makes the
        # individual stores atomic, but "fired bumped, window not yet
        # re-armed" interleavings are real — one lock, held only around the
        # field accesses (never across on_timeout), removes the class of bug
        # (jaxlint: cross-thread-mutation-without-lock).
        self._lock = threading.Lock()

    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        self._last_pat = self._last_progress = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def pat(self) -> None:
        """Mark progress (call once per completed step)."""
        with self._lock:
            self._pats += 1
            self._last_pat = self._last_progress = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def elapsed(self) -> float:
        with self._lock:
            return time.monotonic() - self._last_pat

    @property
    def progress_elapsed(self) -> float:
        """Seconds since the last pat() — the TRUE no-progress figure.
        Unlike :attr:`elapsed`'s clock, this one is never re-armed by a
        fire: after a SIGTERM recovery attempt the run is still hung, and
        the liveness heartbeat must keep saying so."""
        with self._lock:
            return time.monotonic() - self._last_progress

    def _run(self) -> None:
        window = self.timeout
        pats_at_fire = -1
        while not self._stop.wait(self.poll_interval):
            if self.on_patrol is not None:
                try:
                    self.on_patrol(self.progress_elapsed)
                except Exception:
                    pass  # liveness plumbing must never wedge the watchdog
            fire = False
            with self._lock:
                if self.fired >= self.max_fires:
                    return
                if pats_at_fire >= 0 and self._pats > pats_at_fire:
                    window = self.timeout  # a REAL pat since the fire: de-escalate
                    pats_at_fire = -1
                if time.monotonic() - self._last_pat > window:
                    fire = True
                    self.fired += 1
                    pats_at_fire = self._pats
            if fire:
                try:
                    # Outside the lock: on_timeout may run arbitrary trainer
                    # code (save, log) that must not deadlock against pat().
                    self.on_timeout()
                except Exception:
                    pass  # the watchdog must never take the process down itself
                with self._lock:
                    self._last_pat = time.monotonic()  # re-arm for next fire
                window = self.timeout * self.escalation_factor

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
