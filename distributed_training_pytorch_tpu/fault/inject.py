"""Deterministic fault injection — recovery paths as first-class test targets.

A :class:`FaultPlan` is a schedule of :class:`FaultEvent`\\ s, each naming an
injection point (``kind``) plus optional match criteria (epoch, step) and a
firing budget (``count``). Components that own a recovery path query the plan
at their injection point and act only when an event matches — with no plan
(the production default) every query is a cheap ``None`` check.

Injection points wired into the framework:

=====================  ======================================================
``"sigterm"``          ``Trainer.train_epoch`` sends the process a real
                       SIGTERM at (epoch, step) — exercising the actual
                       preemption handler, collective flag vote, and
                       resumable mid-epoch save.
``"nan_loss"``         ``Trainer.train_epoch`` poisons the batch's floating
                       leaves with NaN before the step — exercising the
                       engine's non-finite guard and the trainer's
                       ``nan_policy``.
``"hang"``             ``Trainer.train_epoch`` sleeps ``payload`` seconds at
                       the step — exercising the :class:`~.watchdog.
                       StepWatchdog` hung-step path.
``"checkpoint_write"`` ``CheckpointManager`` raises :class:`InjectedFault`
                       (an ``OSError``) at save initiation — exercising the
                       bounded-retry/backoff path. ``count=N`` fails the
                       first N attempts.
``"corrupt_checkpoint"`` ``CheckpointManager`` corrupts the checkpoint it
                       just committed (via :func:`corrupt_checkpoint`) —
                       exercising integrity validation and the
                       newest-valid-fallback restore.
``"corrupt_record"``   :class:`CorruptingSource` raises
                       ``data.records.CorruptRecordError`` for matching
                       record indices — exercising loader skip-and-count.
``"slow_chip"``        The trainer's straggler sampling point delays one
                       named local device's shard arrival by a configured
                       amount (``payload={"device": id, "delay_ms": ms}``)
                       — a deterministic degraded chip, exercising the
                       per-chip straggler attribution and the fleet
                       controller's exclude-and-replan leg without real
                       hardware asymmetry. Queried via :meth:`FaultPlan.
                       slow_chip` at sync points, NOT a step kind: it must
                       not force chained windows into single-step fallback.
=====================  ======================================================

Determinism: events match on exact (epoch, step) when given, fire at most
``count`` times, and the plan records every firing in ``fired`` — a test can
assert both that the fault happened and that recovery followed.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from typing import Any


class InjectedFault(OSError):
    """A simulated transient I/O failure (retryable, like ENOSPC or a blip
    on a network filesystem)."""


@dataclasses.dataclass
class FaultEvent:
    """One scheduled failure. ``epoch``/``step`` of ``None`` match anything;
    ``count`` is the remaining firing budget (decremented per firing)."""

    kind: str
    epoch: int | None = None
    step: int | None = None
    count: int = 1
    payload: Any = None


class FaultPlan:
    """A deterministic schedule of failures, queried at injection points.

    Build with :meth:`add` (chainable)::

        plan = (FaultPlan()
                .add("sigterm", epoch=0, step=3)
                .add("checkpoint_write", count=2))
    """

    def __init__(self, events: tuple[FaultEvent, ...] | list | None = None):
        self.events: list[FaultEvent] = list(events or [])
        self.fired: list[tuple[str, dict]] = []

    def add(
        self,
        kind: str,
        *,
        epoch: int | None = None,
        step: int | None = None,
        count: int = 1,
        payload: Any = None,
    ) -> "FaultPlan":
        self.events.append(
            FaultEvent(kind, epoch=epoch, step=step, count=count, payload=payload)
        )
        return self

    def fires(
        self, kind: str, *, epoch: int | None = None, step: int | None = None
    ) -> FaultEvent | None:
        """Consume and return the first matching event with budget left,
        else ``None``. A criterion set on the event must equal the queried
        value; unset criteria match anything."""
        for ev in self.events:
            if ev.kind != kind or ev.count <= 0:
                continue
            if ev.epoch is not None and ev.epoch != epoch:
                continue
            if ev.step is not None and ev.step != step:
                continue
            ev.count -= 1
            self.fired.append((kind, {"epoch": epoch, "step": step}))
            return ev
        return None

    def count_fired(self, kind: str) -> int:
        return sum(1 for k, _ in self.fired if k == kind)

    # Step-loop injection kinds — the ones Trainer.train_epoch queries per
    # step. (checkpoint/record kinds fire in other components and don't
    # constrain the step loop's execution shape.)
    STEP_KINDS = ("sigterm", "hang", "nan_loss")

    def active_in_window(self, epoch: int, start: int, stop: int) -> bool:
        """True when any step-loop event with budget left COULD fire at some
        step in ``[start, stop)`` of ``epoch``. Non-consuming — this is the
        trainer's pre-dispatch query deciding whether a chained window must
        fall back to single-step execution so the per-step injection points
        actually run (a whole-window device program has no per-step host
        hook to inject at)."""
        for ev in self.events:
            if ev.kind not in self.STEP_KINDS or ev.count <= 0:
                continue
            if ev.epoch is not None and ev.epoch != epoch:
                continue
            if ev.step is None or start <= ev.step < stop:
                return True
        return False

    # -- injection-point helpers ------------------------------------------

    def maybe_raise(self, kind: str, **ctx) -> None:
        """Raise :class:`InjectedFault` when an event matches (checkpoint
        write-failure injection point)."""
        ev = self.fires(kind, **ctx)
        if ev is not None:
            raise InjectedFault(
                f"injected {kind} fault"
                + (f" (payload={ev.payload!r})" if ev.payload is not None else "")
            )

    def slow_chip(
        self, device_ids, *, epoch: int | None = None
    ) -> tuple[int, float] | None:
        """Degraded-chip query at a straggler sampling point: returns
        ``(device_id, delay_s)`` for the first matching ``slow_chip`` event
        whose named device is among ``device_ids``, else ``None``.

        Membership is checked BEFORE the budget is consumed: a plan naming
        an excluded/absent device (the post-replan topology after the
        controller dropped the slow chip) must stay inert, not burn its
        budget against devices it can no longer slow."""
        ids = {int(d) for d in device_ids}
        for ev in self.events:
            if ev.kind != "slow_chip" or ev.count <= 0:
                continue
            if ev.epoch is not None and ev.epoch != epoch:
                continue
            payload = ev.payload if isinstance(ev.payload, dict) else {}
            dev = int(payload.get("device", -1))
            if dev not in ids:
                continue
            ev.count -= 1
            self.fired.append(("slow_chip", {"epoch": epoch, "device": dev}))
            return dev, float(payload.get("delay_ms", 0.0)) / 1e3
        return None

    def maybe_sigterm(self, *, epoch: int, step: int) -> bool:
        """Deliver a real SIGTERM to this process when scheduled — the same
        signal a cloud scheduler sends ahead of eviction."""
        if self.fires("sigterm", epoch=epoch, step=step) is None:
            return False
        os.kill(os.getpid(), signal.SIGTERM)
        return True


def corrupt_checkpoint(path: str, *, mode: str = "truncate") -> str:
    """Damage a committed checkpoint directory in place; returns the file hit.

    ``mode="truncate"`` halves the largest file (a torn write — the classic
    crash-during-save artifact); ``"flip"`` inverts one byte mid-file (silent
    media/transfer corruption); ``"delete"`` removes the file entirely.
    """
    if mode not in ("truncate", "flip", "delete"):
        raise ValueError(f"mode must be truncate|flip|delete, got {mode!r}")
    victim, size = None, -1
    for dirpath, _, files in os.walk(path):
        for f in files:
            if f == "manifest.dtp.json":
                # corrupt checkpoint DATA, not the integrity manifest — a torn
                # write damages payload bytes; the manifest is tiny and fsync'd
                continue
            fp = os.path.join(dirpath, f)
            s = os.path.getsize(fp)
            if s > size:
                victim, size = fp, s
    if victim is None:
        raise FileNotFoundError(f"no files to corrupt under {path}")
    if mode == "truncate":
        with open(victim, "rb+") as f:  # jaxlint: disable=file-write-without-rank-gate -- fault-injection harness: deliberately corrupts checkpoint bytes in single-process tests
            f.truncate(max(0, size // 2))
    elif mode == "flip":
        with open(victim, "rb+") as f:  # jaxlint: disable=file-write-without-rank-gate -- fault-injection harness: deliberately corrupts checkpoint bytes in single-process tests
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
    else:
        os.remove(victim)
    return victim


class CorruptingSource:
    """Wrap a data source so scheduled records read as corrupt.

    Matching uses the plan's ``step`` criterion as the *record index*. The
    raised error is :class:`~distributed_training_pytorch_tpu.data.records.
    CorruptRecordError`, exactly what a truncated/garbled record produces —
    so the loader's skip-and-count path sees the real exception type.
    """

    def __init__(self, source, plan: FaultPlan):
        self.source = source
        self.plan = plan
        self.transform = getattr(source, "transform", None)

    def __len__(self) -> int:
        return len(self.source)

    def __getitem__(self, index: int):
        from distributed_training_pytorch_tpu.data.records import CorruptRecordError

        if self.plan.fires("corrupt_record", step=int(index)) is not None:
            raise CorruptRecordError(f"injected corrupt record at index {int(index)}")
        return self.source[index]
