"""Fault-tolerance subsystem: deterministic fault injection + hung-step watchdog.

Production TPU fleets preempt, lose filesystems mid-write, and feed training
jobs the occasional corrupt record; this package makes every one of those
paths *testable in-process on CPU*:

* :mod:`~.inject` — :class:`FaultPlan`, a deterministic schedule of injected
  failures (SIGTERM mid-epoch, transient checkpoint-write errors, corrupt
  checkpoint on disk, corrupt data records, NaN loss, hung steps) consumed by
  the trainer / checkpoint manager / data sources at their injection points.
* :mod:`~.watchdog` — :class:`StepWatchdog`, a wall-clock monitor that turns
  a hung step into a preemption-style save instead of a silent stall.
"""

from distributed_training_pytorch_tpu.fault.inject import (  # noqa: F401
    CorruptingSource,
    FaultEvent,
    FaultPlan,
    InjectedFault,
    corrupt_checkpoint,
)
from distributed_training_pytorch_tpu.fault.watchdog import StepWatchdog  # noqa: F401
