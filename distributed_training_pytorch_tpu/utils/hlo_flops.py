"""Matmul/conv FLOP accounting straight from optimized HLO text.

Walks a compiled executable's ``as_text()`` for every ``convolution`` and
``dot`` instruction (fused bodies included — each ``%name`` defines once) and
computes the FLOPs XLA's own cost model attributes to it: ``2 * out_elems *
reduction_size``, reduction = rhs spatial x input-feature (convs, from
``dim_labels`` — the HLO rhs kernel already carries C_in/groups for grouped
convs, so NO further feature_group_count division; regression-tested) or the
contracting-dims product (dots). The sum is the program's *executed* MXU FLOPs — what the
compiler kept after folding, as opposed to the layer-formula *nominal* count
an eager executor (the torch reference) performs.

Born from the r4 VGG16 itemization (``scripts/itemize_flops.py``): the
long-suspected "XLA undercounts conv backward" gap turned out to be the
compiler legitimately strength-reducing the 32x32 config's degenerate
classifier (a 1x1 feature map replicated to 7x7 by adaptive pool folds from
a 25088-wide to an effective 512-wide GEMM). fwd/dgrad/wgrad conv FLOPs
reconcile per-instruction.
"""

from __future__ import annotations

import re

__all__ = [
    "itemize_hlo_matmul_flops",
    "executed_matmul_flops",
    "xla_cost_analysis",
    "bytes_accessed",
    "arithmetic_intensity",
    "aval_bytes",
]


def xla_cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one flat dict — old jaxlib
    returns a single-element list of per-program dicts, new jaxlib the dict
    itself."""
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost)


def bytes_accessed(compiled) -> float | None:
    """XLA's ``bytes accessed`` estimate for a compiled executable: total
    HBM traffic (operand reads + output writes, post-fusion) the cost model
    attributes to the program. The memory-side twin of the ``flops`` entry —
    together they place a program on the roofline. For a ``lax.scan``-chained
    program the body is counted once, matching the FLOP convention. None when
    the backend reports no cost analysis (e.g. some relay/plugin paths)."""
    value = xla_cost_analysis(compiled).get("bytes accessed")
    return float(value) if value is not None else None


def arithmetic_intensity(compiled, *, flops: float | None = None) -> float | None:
    """FLOPs per HBM byte — the roofline x-coordinate. Above the machine's
    peak_FLOPs/peak_bandwidth ridge point a program can be compute-bound;
    below it the bandwidth floor caps MFU no matter the dtype. Mixed
    precision moves BOTH axes (bf16 halves the bytes of every activation/
    weight access and doubles MXU peak), which is why the precision sweep in
    ``docs/performance.md`` reports intensity per dtype.

    ``flops`` overrides the numerator (e.g. the analytic model count);
    default is ``cost_analysis()``'s executed estimate. None when either
    side of the ratio is unavailable or zero."""
    denom = bytes_accessed(compiled)
    if not denom:
        return None
    numer = flops if flops is not None else float(xla_cost_analysis(compiled).get("flops", 0.0))
    if not numer:
        return None
    return numer / denom

DEF_RE = re.compile(r"^(?:ROOT )?%([\w.\-]+) = (\w+)\[([0-9,]*)\]")

# Element sizes for the dtypes HLO shapes name; anything unlisted (tuples,
# opaque tokens) falls back to 4 — per-op bytes are a roofline estimate, not
# an allocator accounting.
DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
def aval_bytes(shape, dtype) -> float:
    """Byte size of one array leaf at the HLO dtype widths above — the
    sizing the static audit's donation report uses (``analysis.hlo_audit``:
    undonated bytes of param/optimizer-state inputs), so a lint report and a
    per-op roofline row account memory with the same table. ``dtype``
    accepts numpy/jax dtypes or names; anything unmappable (extended dtypes
    like typed PRNG keys) falls back to 4 bytes/element — an estimate, same
    contract as the per-op ``bytes`` rows."""
    import numpy as np

    n = 1
    for dim in shape:
        n *= int(dim)
    try:
        name = np.dtype(dtype).name
    except TypeError:
        return float(n * 4)
    if name == "bool":
        hlo = "pred"
    elif name.startswith("float"):
        hlo = "f" + name[len("float"):]
    elif name.startswith("bfloat"):
        hlo = "bf" + name[len("bfloat"):]
    elif name.startswith("uint"):
        hlo = "u" + name[len("uint"):]
    elif name.startswith("int"):
        hlo = "s" + name[len("int"):]
    elif name.startswith("complex"):
        hlo = "c" + name[len("complex"):]
    else:
        hlo = name
    return float(n * DTYPE_BYTES.get(hlo, 4))


CONV_RE = re.compile(r" convolution\((.*?)\), window={(.*?)}, dim_labels=(\S+?)[,\s]")
DOT_RE = re.compile(r" dot\((.*?)\),.*?lhs_contracting_dims={([0-9,]*)}")
OPERAND_RE = re.compile(r"%([\w.\-]+)")
OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def _dims(s: str) -> list[int]:
    return [int(x) for x in s.split(",") if x] if s else []


def _numel(dims: list[int]) -> int:
    n = 1
    for x in dims:
        n *= x
    return n


def itemize_hlo_matmul_flops(hlo_text: str) -> list[dict]:
    """Per-instruction rows: ``{name, kind, out_elems, reduction, flops,
    bytes, dim_labels, op_name}`` for every conv/dot in the module.

    ``bytes`` is the op's roofline denominator — output write + operand reads
    at the HLO shapes' dtypes (operands with unparsed shapes contribute 0) —
    so ``flops / bytes`` places the instruction on the roofline next to the
    whole-program ``arithmetic_intensity`` figure. Joined into profile
    reports by ``profiling.report.flops_index``."""
    shapes: dict[str, tuple[list[int], str]] = {}
    stripped = [line.strip() for line in hlo_text.splitlines()]
    for line in stripped:
        m = DEF_RE.match(line)
        if m:
            shapes[m.group(1)] = (_dims(m.group(3)), m.group(2))

    def op_bytes(out_dims: list[int], out_dtype: str, operand_names: list[str]) -> float:
        total = _numel(out_dims) * DTYPE_BYTES.get(out_dtype, 4)
        for op in operand_names:
            if op in shapes:
                dims, dtype = shapes[op]
                total += _numel(dims) * DTYPE_BYTES.get(dtype, 4)
        return float(total)

    rows: list[dict] = []
    for line in stripped:
        d = DEF_RE.match(line)
        if not d:
            continue
        name, out_dtype, out = d.group(1), d.group(2), _dims(d.group(3))
        out_elems = _numel(out)
        opname = OPNAME_RE.search(line)
        opname = opname.group(1) if opname else ""
        m = CONV_RE.search(line)
        if m:
            ops = OPERAND_RE.findall(m.group(1))
            rhs = shapes.get(ops[1]) if len(ops) > 1 else None
            if rhs is None:
                continue
            rhs_dims = rhs[0]
            labels = m.group(3)  # e.g. b01f_01io->b01f
            rhs_spec = labels.split("_")[1].split("-")[0]
            # Reduction per output element = rhs spatial dims x rhs input
            # feature ('i'); 'o' is the output-feature dim, not reduced.
            red = 1
            for pos, ch in enumerate(rhs_spec):
                if ch.isdigit() or ch == "i":
                    red *= rhs_dims[pos]
            # Grouped convs need NO division here: the HLO rhs kernel's
            # input-feature dim is already C_in/groups (verified on a
            # groups=8 3x3 conv: rhs 'i' dim = 1).
            rows.append(dict(name=name, kind="conv", out_elems=out_elems,
                             reduction=red, flops=2.0 * out_elems * red,
                             bytes=op_bytes(out, out_dtype, ops[:2]),
                             dim_labels=labels, op_name=opname))
            continue
        m = DOT_RE.search(line)
        if m:
            ops = OPERAND_RE.findall(m.group(1))
            lhs = shapes.get(ops[0]) if ops else None
            if lhs is None:
                continue
            red = 1
            for dim in _dims(m.group(2)):
                red *= lhs[0][dim]
            rows.append(dict(name=name, kind="dot", out_elems=out_elems,
                             reduction=red, flops=2.0 * out_elems * red,
                             bytes=op_bytes(out, out_dtype, ops[:2]),
                             dim_labels="", op_name=opname))
    return rows


def executed_matmul_flops(compiled) -> float | None:
    """Executed MXU FLOPs of a jax compiled executable (sum over conv/dot
    instructions of its optimized HLO). For a ``lax.scan``-chained program
    this counts the body once, matching ``cost_analysis()``'s convention.

    Returns None when the counting convention does not apply: XLA:TPU lowers
    transformer ``dot_general``s to *windowed* convolutions (e.g.
    ``window={size=3x1x12 pad=2_2x0_0x11_11 rhs_reversal=...}``) whose
    window taps are mostly padding — the kernel-spatial formula then counts
    phantom work (measured 6.7x cost_analysis on ViT-B). The guard: accept
    the sum only when it reconciles with ``cost_analysis()`` (which also
    counts VPU elementwise, so a valid matmul-only sum lands below it).

    A parser regression is NOT silent (ADVICE r4): the documented
    windowed-conv mismatch only ever OVER-counts (phantom padding taps), so
    the silent None is reserved for ratios above the band; zero matches, or a
    ratio below it (an undercount — e.g. one of the two regexes breaking
    while the other still matches), warns loudly.

    Custom calls (Pallas kernels) are opaque to both this walk and to
    ``cost_analysis()`` — a flash-attention program's counted FLOPs exclude
    the attention matmuls entirely (measured: BASELINE.md "LM FLOP-counter
    reconciliation"); comparisons against nominal counts must add the
    kernel's analytic FLOPs back."""
    total = sum(r["flops"] for r in itemize_hlo_matmul_flops(compiled.as_text()))
    cost = xla_cost_analysis(compiled)
    xla = float(cost.get("flops", 0.0))
    if total == 0.0 and xla > 1e9:
        import warnings

        warnings.warn(
            "executed_matmul_flops: no convolution/dot instructions matched in "
            f"an HLO module whose cost_analysis reports {xla:.2e} flops — the "
            "HLO text format likely changed and the parser needs updating "
            "(this is a parser regression, not the windowed-conv convention "
            "mismatch)."
        )
        return None
    if xla > 0:
        if total == 0.0:
            return None  # matmul-free (or trivial) program; warned above if big
        if total / xla < 0.3:
            import warnings

            warnings.warn(
                f"executed_matmul_flops: matched conv/dot sum {total:.2e} is "
                f"below 0.3x cost_analysis ({xla:.2e}) — an UNDER-count, which "
                "the windowed-conv convention mismatch cannot produce; likely "
                "a partial HLO-parser regression (one instruction form no "
                "longer matching)."
            )
            return None
        if total / xla > 1.1:
            return None  # documented windowed-conv overcount (see docstring)
    return total
