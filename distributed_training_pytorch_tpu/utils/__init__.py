from distributed_training_pytorch_tpu.utils.logger import Logger  # noqa: F401
