"""Back-compat shim: the tracing/profiling surface moved to the first-class
``profiling/`` package (ISSUE 6) — trace capture, the xplane codec, category
attribution, ``StepProfile`` reports, hot-path capture, and the perf gate all
live there now. Existing ``utils.profiling`` imports keep working.

New code should import ``distributed_training_pytorch_tpu.profiling``.
"""

from distributed_training_pytorch_tpu.profiling.trace import (  # noqa: F401
    annotate,
    latest_trace_file,
    top_ops,
    trace,
)

__all__ = ["trace", "annotate", "top_ops", "latest_trace_file"]
