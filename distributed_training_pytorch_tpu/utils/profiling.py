"""Tracing / profiling subsystem.

TPU-native analog of the reference's observability hooks — the tqdm live
progress bars (``/root/reference/trainer/trainer.py:143,186``) and the NCCL
flight-recorder buffer (``/root/reference/run.sh:8``, 100 MiB
``TORCH_NCCL_TRACE_BUFFER_SIZE`` for post-mortem collective traces). On TPU the
equivalent is an XLA/XProf device trace: ``jax.profiler`` captures per-op
device timelines (including collective ops), viewable in TensorBoard's profile
plugin or summarized directly with :func:`top_ops`.

Surface:

* :func:`trace` — context manager around ``jax.profiler.start_trace`` /
  ``stop_trace``; writes a TensorBoard-loadable trace under ``log_dir``.
* :func:`annotate` — named region inside a trace (shows up on the host
  timeline; use around step phases: data load, step, checkpoint).
* :func:`top_ops` — parse the newest captured trace into a list of
  ``(op_name, self_time_us, occurrences)`` sorted by device self-time, so a
  trace can be inspected headlessly (no TensorBoard UI needed).
* ``Trainer(profile_dir=...)`` (see ``trainer/trainer.py``) traces a window of
  training steps automatically.
"""

from __future__ import annotations

import glob
import gzip
import os
from contextlib import contextmanager
from typing import Iterator

import jax

__all__ = ["trace", "annotate", "top_ops", "latest_trace_file"]


@contextmanager
def trace(log_dir: str) -> Iterator[str]:
    """Capture a device+host trace of the enclosed block into ``log_dir``.

    Yields the log dir. The result is a standard XProf/TensorBoard trace
    (``plugins/profile/<run>/*.xplane.pb``); inspect with TensorBoard or
    :func:`top_ops`.
    """
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_link=False)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region (context manager): ``with annotate("train_step"):``.

    Thin alias of ``jax.profiler.TraceAnnotation`` so user code only imports
    this module.
    """
    return jax.profiler.TraceAnnotation(name)


def latest_trace_file(log_dir: str) -> str | None:
    """Path of the newest ``*.xplane.pb`` under ``log_dir`` (or None)."""
    paths = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True)
    return max(paths, key=os.path.getmtime) if paths else None


# -- minimal xplane.pb reader -------------------------------------------------
# The XProf trace is an XSpace protobuf (tensorflow/tsl xplane.proto). The
# pinned tensorboard_plugin_profile's generated protos are incompatible with
# the installed protobuf runtime, so decode the wire format directly — the
# schema subset needed for an op table is tiny:
#   XSpace.planes=1 / XPlane{name=2, lines=3, event_metadata=4(map)}
#   XLine{name=2, events=4} / XEvent{metadata_id=1, duration_ps=3}
#   XEventMetadata(map entry value){id=1, name=2}


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) for one protobuf message."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _varint(buf, i)
        elif wire == 2:
            ln, i = _varint(buf, i)
            val = buf[i : i + ln]
            i += ln
        elif wire == 5:
            val = buf[i : i + 4]
            i += 4
        elif wire == 1:
            val = buf[i : i + 8]
            i += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def top_ops(
    log_dir: str, *, limit: int = 20, line: str | None = None
) -> list[tuple[str, float, int]]:
    """Summarize the newest trace in ``log_dir``: device ops by total time.

    Returns ``[(op_name, total_time_us, occurrences), ...]`` over the device
    (TPU/GPU) planes, sorted descending — a headless op profile; no
    TensorBoard server needed.

    ``line`` filters to one named trace line. The TPU device plane carries
    several: ``"XLA Ops"`` is the synchronous critical path (its events sum
    to wall step time), ``"Async XLA Ops"`` holds overlapped DMA/prefetch
    copies whose durations span their async windows — summing across both
    double-counts overlap, so per-op accounting should pass
    ``line="XLA Ops"``. Default (None) keeps every line, preserving the
    "everything the device did" view.
    """
    path = latest_trace_file(log_dir)
    if path is None:
        raise FileNotFoundError(f"no *.xplane.pb under {log_dir}")
    with open(path, "rb") as f:
        space = f.read()
    totals: dict[str, list[float]] = {}
    for field, _, plane_buf in _fields(space):
        if field != 1:  # XSpace.planes
            continue
        plane_name, meta_names, lines = "", {}, []
        for pf, _, pv in _fields(plane_buf):
            if pf == 2:
                plane_name = pv.decode("utf-8", "replace")
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:  # map<int64, XEventMetadata> entry
                mid, mname = 0, ""
                for ef, _, ev in _fields(pv):
                    if ef == 2:  # value: XEventMetadata
                        for mf, _, mv in _fields(ev):
                            if mf == 1:
                                mid = mv
                            elif mf == 2:
                                mname = mv.decode("utf-8", "replace")
                meta_names[mid] = mname
        if "TPU" not in plane_name and "GPU" not in plane_name:
            continue
        for line_buf in lines:
            line_name, events = "", []
            for lf, _, lv in _fields(line_buf):
                if lf == 2:
                    line_name = lv.decode("utf-8", "replace")
                elif lf == 4:  # XLine.events
                    events.append(lv)
            if line is not None and line_name != line:
                continue
            for lv in events:
                mid = dur_ps = 0
                for ef, _, ev in _fields(lv):
                    if ef == 1:
                        mid = ev
                    elif ef == 3:
                        dur_ps = ev
                name = meta_names.get(mid, f"op#{mid}")
                acc = totals.setdefault(name, [0.0, 0])
                acc[0] += dur_ps / 1e6  # ps -> us
                acc[1] += 1
    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])
    return [(name, round(t, 1), int(n)) for name, (t, n) in ranked[:limit]]
