"""Optional TensorBoard metrics writer — SURVEY.md §5.5's named upgrade.

The reference logs metrics only to console + file (``utils/logger.py``); the
TPU-equivalent observability stack adds a TensorBoard scalar stream next to
the profiler traces (``utils.profiling``), so one TensorBoard instance shows
both. Backend: ``tensorboardX`` when importable, else a no-op (the framework
never hard-depends on it — the Trainer's precision scalars
(``precision/loss_scale``, ``precision/skipped_steps`` under a dynamic loss
scale) ride the same contract and stay silent without the backend). Process
0 writes; other hosts get a no-op writer — metrics are global (collectively
reduced) so one writer sees everything.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import numpy as np

__all__ = ["MetricsWriter"]


class MetricsWriter:
    """Scalar writer: ``writer.write(step, {"loss": ...}, prefix="train")``."""

    def __init__(self, log_dir: str | None):
        self._log_dir = log_dir
        self._writer = None
        self._dead = False  # a failed backend write disables the writer for good
        self.reopen()

    def reopen(self) -> None:
        """(Re)create the backend writer — lets a closed writer come back for a
        re-entered ``train()`` instead of silently dropping all later scalars.
        A writer disabled by a backend failure stays disabled (the filesystem
        that failed once is not coming back mid-run; retrying every scalar
        would spam the failure)."""
        if (
            self._writer is not None
            or self._dead
            or not self._log_dir
            or jax.process_index() != 0
        ):
            return
        try:
            from tensorboardX import SummaryWriter

            self._writer = SummaryWriter(self._log_dir)
        except ImportError:
            pass  # stay a no-op; console/file logging still covers metrics

    @property
    def active(self) -> bool:
        return self._writer is not None

    def write(self, step: int, metrics: Mapping, prefix: str = "") -> None:
        """Write one scalar per finite entry. Values are coerced ONCE here —
        python/numpy/jax scalars and 0-d (or 1-element) arrays all become a
        plain float before touching the backend, so ``add_scalar`` never sees
        a device array or a numpy dtype it would re-coerce per call. Entries
        that are not scalar, or not finite (a NaN epoch loss under
        ``nan_policy``, an Inf ``update_ratio`` on a poisoned step), are
        skipped: a bad value must cost one missing curve point, never the
        writer (and with it every later scalar of the run).

        Backend failures follow the event-log policy (try once, then
        disable): a full disk or a dead filesystem under the TensorBoard
        directory warns and permanently disables this writer — metrics are
        observability, never the reason training dies."""
        if self._writer is None:
            return
        step = int(step)
        try:
            for key, value in metrics.items():
                try:
                    value = float(np.asarray(value).reshape(()))
                except (TypeError, ValueError):
                    continue  # non-scalar entries are not TensorBoard material
                if not math.isfinite(value):
                    continue  # tolerate NaN/Inf: skip the point, keep the writer
                tag = f"{prefix}/{key}" if prefix else key
                self._writer.add_scalar(tag, value, step)
            self._writer.flush()
        except Exception as e:  # noqa: BLE001 — any backend failure, same policy
            self._dead = True
            writer, self._writer = self._writer, None
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — already failing; best-effort close
                pass
            import warnings

            warnings.warn(
                f"MetricsWriter disabled — TensorBoard write to "
                f"{self._log_dir!r} failed: {e}"
            )

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
