"""Optional TensorBoard metrics writer — SURVEY.md §5.5's named upgrade.

The reference logs metrics only to console + file (``utils/logger.py``); the
TPU-equivalent observability stack adds a TensorBoard scalar stream next to
the profiler traces (``utils.profiling``), so one TensorBoard instance shows
both. Backend: ``tensorboardX`` when importable, else a no-op (the framework
never hard-depends on it — the Trainer's precision scalars
(``precision/loss_scale``, ``precision/skipped_steps`` under a dynamic loss
scale) ride the same contract and stay silent without the backend). Process
0 writes; other hosts get a no-op writer — metrics are global (collectively
reduced) so one writer sees everything.
"""

from __future__ import annotations

from typing import Mapping

import jax

__all__ = ["MetricsWriter"]


class MetricsWriter:
    """Scalar writer: ``writer.write(step, {"loss": ...}, prefix="train")``."""

    def __init__(self, log_dir: str | None):
        self._log_dir = log_dir
        self._writer = None
        self.reopen()

    def reopen(self) -> None:
        """(Re)create the backend writer — lets a closed writer come back for a
        re-entered ``train()`` instead of silently dropping all later scalars."""
        if self._writer is not None or not self._log_dir or jax.process_index() != 0:
            return
        try:
            from tensorboardX import SummaryWriter

            self._writer = SummaryWriter(self._log_dir)
        except ImportError:
            pass  # stay a no-op; console/file logging still covers metrics

    @property
    def active(self) -> bool:
        return self._writer is not None

    def write(self, step: int, metrics: Mapping, prefix: str = "") -> None:
        if self._writer is None:
            return
        for key, value in metrics.items():
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue  # non-scalar entries are not TensorBoard material
            tag = f"{prefix}/{key}" if prefix else key
            self._writer.add_scalar(tag, value, int(step))
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
