"""Console + file logger.

Capability twin of the reference ``utils/logger.py:6-33`` (named stdlib logger,
INFO level, timestamped format, console + file handlers, and a
``log(message, log_type)`` method mapping warning/error/else -> level), with
two deliberate multi-host fixes (SURVEY.md §2e):

* the reference deletes and re-opens the *same* log file from every rank
  (``utils/logger.py:11-12`` + ``main.py:5``) — a race on shared filesystems.
  Here only process 0 attaches the file handler; other processes keep console
  output prefixed with their process index.
* file truncation happens via mode ``"w"`` on the handler instead of an
  explicit ``os.remove`` (same observable behavior: a fresh file per run).
"""

from __future__ import annotations

import logging
import os
import sys

import jax

_FORMAT = "%(asctime)s | %(name)s | %(levelname)s | %(message)s"


class Logger:
    """``Logger(name, log_file)`` — the construction signature of the
    reference (``utils/logger.py:6``: name + log path)."""

    def __init__(
        self,
        name: str,
        log_file: str | None = None,
        *,
        level: int = logging.INFO,
        all_processes_to_file: bool = False,
    ):
        self.name = name
        self.log_file = log_file
        self._logger = logging.getLogger(f"{name}.{os.getpid()}")
        self._logger.setLevel(level)
        self._logger.propagate = False
        self._logger.handlers.clear()

        fmt = _FORMAT
        if jax.process_count() > 1:
            fmt = f"%(asctime)s | p{jax.process_index()} | %(name)s | %(levelname)s | %(message)s"
        formatter = logging.Formatter(fmt)
        console = logging.StreamHandler(sys.stdout)
        console.setFormatter(formatter)
        self._logger.addHandler(console)

        if log_file is not None and (all_processes_to_file or jax.process_index() == 0):
            if all_processes_to_file and jax.process_count() > 1:
                root, ext = os.path.splitext(log_file)
                log_file = f"{root}.p{jax.process_index()}{ext}"
            os.makedirs(os.path.dirname(os.path.abspath(log_file)), exist_ok=True)
            file_handler = logging.FileHandler(log_file, mode="w")
            file_handler.setFormatter(formatter)
            self._logger.addHandler(file_handler)
            self.log_file = log_file

    def log(self, message: str, log_type: str = "info") -> None:
        """warning/error -> those levels, anything else -> info
        (``utils/logger.py:27-33``)."""
        if log_type == "warning":
            self._logger.warning(message)
        elif log_type == "error":
            self._logger.error(message)
        else:
            self._logger.info(message)

    # Convenience aliases so the Logger is drop-in usable as a stdlib-ish logger.
    def info(self, message: str) -> None:
        self._logger.info(message)

    def warning(self, message: str) -> None:
        self._logger.warning(message)

    def error(self, message: str) -> None:
        self._logger.error(message)
