"""TPU performance defaults.

One documented switch instead of the reference's NCCL env tuning block
(``/root/reference/run.sh:1-8`` — NCCL_ALGO/PROTO/P2P_LEVEL etc.): on TPU the
XLA compiler owns scheduling and collective selection, so the only knob worth
setting globally is the PRNG implementation.
"""

from __future__ import annotations

import jax

__all__ = ["enable_fast_rng", "tpu_compiler_options"]


def tpu_compiler_options() -> dict:
    """Per-compile XLA:TPU options worth setting for conv-heavy steps.

    ``xla_tpu_scoped_vmem_limit_kib=49152``: raises the compiler's scoped-VMEM
    budget from its ~16MB default so conv/weight prefetch fusions double-buffer
    deeper — measured 84.4 -> 76.8 ms/step (+9%) on the VGG16/CIFAR bench step
    on v5e (sweep in-repo: 32768/49152/65536/98304 -> 49152 best). Pass to
    ``TrainEngine.compile_train_step(compiler_options=...)`` (per-compile; the
    relay forwards these where global XLA_FLAGS cannot carry TPU-only flags).
    Returns {} on non-TPU backends.
    """
    if jax.default_backend() != "tpu":
        return {}
    return {"xla_tpu_scoped_vmem_limit_kib": "49152"}


def enable_fast_rng() -> None:
    """Use the hardware RBG-based PRNG for ``jax.random`` keys.

    JAX's default ``threefry2x32`` is counter-based and fully reproducible
    across backends, but costs real MXU/VPU time when a train step draws large
    dropout masks every step (measured ~8% of the VGG16/CIFAR step on v5e).
    ``rbg`` keys use the TPU's hardware random-bit generator: same
    (key, shape) -> bits determinism within a backend, much cheaper to
    generate.

    Call before any ``jax.random.key`` creation (typically first thing in a
    train script). Tests keep the default threefry for cross-platform
    reproducibility.
    """
    jax.config.update("jax_default_prng_impl", "rbg")
