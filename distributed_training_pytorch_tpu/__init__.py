"""distributed_training_pytorch_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capability surface of
``ducphuongbk01/Distributed-Training-Pytorch`` (reference: ``trainer/trainer.py``,
``example_trainer.py``, ``model/vgg16.py``, ``dataset/example_dataset.py``,
``utils/logger.py``, ``main.py``, ``eval.py``, ``run.sh``): a template-method
trainer with user-overridable hooks, multi-host data-parallel training,
epoch-based orchestration with periodic validation, best/last/periodic
checkpointing with snapshot resume, file+console logging, and a standalone
offline evaluator — rebuilt TPU-first:

* ``parallel``  — device-mesh bootstrap (``jax.distributed`` + ``jax.sharding.Mesh``),
  sharding rules (FSDP / Megatron-TP), ring + Ulysses sequence parallelism,
  GPipe-style pipeline parallelism, GShard-style MoE expert parallelism.
* ``models``    — Flax model zoo (VGG16, ResNet-50, ViT-B/16, ConvNeXt-L).
* ``ops``       — losses, metrics, schedules, Pallas kernels.
* ``train``     — functional ``TrainState`` + jitted train/eval step engine
  (replaces DDP + criterion/optimizer/scheduler mutation).
* ``precision`` — mixed-precision dtype policies (fp32/bf16/fp16 with fp32
  master weights) + dynamic loss scaling as on-device pytree state
  (docs/mixed_precision.md).
* ``data``      — deterministic host-sharded input pipeline with device prefetch
  (replaces ``DistributedSampler`` + ``DataLoader``).
* ``checkpoint``— Orbax-backed best/last/periodic checkpointing with resume,
  crash-consistent atomic commits, integrity validation, and newest-valid
  fallback (docs/fault_tolerance.md).
* ``fault``     — fault-injection harness (``FaultPlan``) + hung-step
  watchdog: preemption, torn saves, NaN steps, and corrupt records as
  tested code paths.
* ``telemetry`` — run observability: structured JSONL event log, goodput
  wall-time buckets (cumulative across kill/resume), on-device train-health
  stats, MFU/roofline fields, anomaly detectors (docs/observability.md).
* ``analysis``  — static analysis: jaxlint (project-specific AST rules with
  audited inline waivers), compiled-program HLO audit (donation aliasing,
  precision leaks, host callbacks), generic ruff/stdlib layer
  (docs/static_analysis.md; gate: ``scripts/static_audit.py``).
* ``memory``    — memory observability: per-buffer HBM attribution from
  ``compiled.memory_analysis()`` (class fractions sum to 1), the OOM
  preflight with batch/microbatch recommendations
  (``Trainer(preflight=...)``), shared live ``memory_stats`` telemetry +
  growth detection (docs/memory.md; gate: ``scripts/memory_probe.py``).
* ``compat``    — JAX version shims (``shard_map`` API move, ambient-mesh
  helpers) so one codebase spans the supported JAX range.
* ``trainer``   — the epoch-loop orchestrator with the reference's 9 hook names.
* ``utils``     — logging, profiling/tracing (``utils.profiling``), TPU perf
  defaults (``utils.tpu``).
"""

__version__ = "0.2.0"

from distributed_training_pytorch_tpu.checkpoint import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    CorruptCheckpointError,
)
from distributed_training_pytorch_tpu.fault import (  # noqa: F401
    FaultPlan,
    StepWatchdog,
)
from distributed_training_pytorch_tpu.parallel.mesh import (  # noqa: F401
    setup_distributed,
    create_mesh,
    shutdown_distributed,
)
from distributed_training_pytorch_tpu.precision import (  # noqa: F401
    DynamicScale,
    NoOpScale,
    Policy,
)
from distributed_training_pytorch_tpu.telemetry import (  # noqa: F401
    AnomalyDetector,
    Telemetry,
)
