"""Resilience layer (ISSUE 5): asynchronous + emergency checkpointing.

Sits between the trainer's save sites and the crash-consistent
:class:`~distributed_training_pytorch_tpu.checkpoint.manager.
CheckpointManager`: a save becomes a millisecond device->host snapshot on
the hot loop plus a background-thread commit through the existing staging +
manifest + atomic-rename machinery, with a newest-wins bounded queue, a
``flush()`` barrier, and a synchronous *emergency* path for SIGTERM /
watchdog saves that must land inside the preemption grace window.

``scripts/chaos_soak.py`` is the subsystem's proof: randomized seeded
SIGTERM/SIGKILL kills (including mid-background-commit) with verified
bit-exact resume. See docs/fault_tolerance.md for the save state machine.
"""

from distributed_training_pytorch_tpu.resilience.async_saver import (  # noqa: F401
    AsyncCheckpointSaver,
    SaveRequest,
    measure_save_stall,
)

__all__ = ["AsyncCheckpointSaver", "SaveRequest", "measure_save_stall"]
