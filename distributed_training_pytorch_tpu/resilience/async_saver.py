"""Asynchronous checkpointing: millisecond hot-loop stalls, background commits.

Every save through :class:`~distributed_training_pytorch_tpu.checkpoint.
manager.CheckpointManager` is durable (staging dir + SHA-256 manifest +
atomic rename) but *synchronous*: on a real model the step loop stalls for
the full serialize + fsync + rename — directly visible in the telemetry
``checkpoint`` goodput bucket. Production TPU stacks (Check-N-Run-style
decoupled checkpointing, Orbax's async/emergency checkpointing) split a save
in two:

1. **Snapshot** (on-thread, fast): ``jax.device_get`` copies the live
   ``TrainState`` into a pinned host pytree — this also *drains* the device
   (the state's in-flight computation must finish before the copy), so the
   snapshot is a consistent point-in-time view no later train step can
   mutate. Only this phase stalls the hot loop.
2. **Commit** (background thread): the host copy runs through the manager's
   existing crash-consistent machinery — ``.staging`` write, integrity
   manifest, atomic rename — off the hot path.

:class:`AsyncCheckpointSaver` implements that split around an existing
manager, with the invariants the recovery machinery depends on:

* **Single committer.** One daemon worker owns every manager call the saver
  issues; the manager is never touched by two threads at once, so there are
  never interleaved staging directories.
* **Bounded queue, newest wins.** At most one commit is in flight and at
  most one snapshot is pending per checkpoint *name*; a newer snapshot of
  the same name replaces the queued one (the superseded host copy is simply
  dropped — it was never visible on disk). Distinct names (``best`` then
  ``last`` at an epoch boundary) queue FIFO, so no policy checkpoint is ever
  silently discarded.
* **Strict ordering.** Commits land in enqueue order through the single
  worker, so directory mtimes — the ``restore_latest_valid`` newest-first
  order — match save order, and a crash mid-commit leaves exactly the
  manager's documented artifacts (an Orbax tmp dir, a complete-but-unrenamed
  staging dir, or a committed checkpoint): ``restore_latest_valid`` always
  sees a consistent tree.
* **flush() barrier.** Blocks until the queue is drained and the last commit
  is fully on disk; background commit *errors* (a save that exhausted its
  retries) surface here — or at the next ``save_async`` — on the caller's
  thread, never silently on the worker.
* **Emergency saves.** :meth:`save_sync` is the SIGTERM / watchdog path:
  flush the in-flight work (never abandon it — a queued save may be the only
  recent durable state), then commit the new snapshot synchronously on the
  calling thread, inside the preemption grace window.

State machine of one save (see docs/fault_tolerance.md for what each crash
point leaves on disk)::

    snapshot --> queued --> committing --> committed
                    \\
                     superseded  (newer same-name snapshot arrived first)

Telemetry: the caller charges only the snapshot time to the ``checkpoint``
goodput bucket; the worker reports each commit's wall time through
``on_commit(name, seconds)`` so the trainer can book it to the
``checkpoint_async`` bucket and emit a ``checkpoint_commit`` event — the
async win is measurable, not just claimed (``bench.py`` ``save_stall`` and
``scripts/chaos_soak.py`` drive it).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping

import jax

from distributed_training_pytorch_tpu.checkpoint import BEST

__all__ = ["AsyncCheckpointSaver", "SaveRequest", "measure_save_stall"]

# SaveRequest lifecycle states (docs/fault_tolerance.md state machine).
SNAPSHOT = "snapshot"
QUEUED = "queued"
COMMITTING = "committing"
COMMITTED = "committed"
SUPERSEDED = "superseded"
FAILED = "failed"


class SaveRequest:
    """One snapshot moving through the save state machine."""

    __slots__ = ("name", "state", "epoch", "kwargs", "status", "snapshot_s", "commit_s")

    def __init__(self, name: str, state: Any, epoch: int, kwargs: dict):
        self.name = name
        self.state = state  # host pytree (device_get'd) — pinned, immutable
        self.epoch = epoch
        self.kwargs = kwargs
        self.status = SNAPSHOT
        self.snapshot_s = 0.0
        self.commit_s = 0.0


def measure_save_stall(manager, state, *, repeats: int = 1, meter=None) -> dict:
    """Time one config's hot-loop save stall, sync vs async, on ``state``.

    The ONE implementation behind both reported figures — ``bench.py``'s
    ``save_stall_ms``/``save_sync_ms`` sweep fields and the chaos soak's
    < 25 % stall acceptance check — so the acceptance metric and the
    benchmark metric cannot drift apart. Returns best-of-``repeats``
    ``{"sync_ms", "stall_ms", "commit_ms", "stall_ratio"}``.

    ``manager`` should be a synchronous ``CheckpointManager`` scratch
    instance (the saves land under names ``stall_sync``/``stall_async``).
    ``meter`` (a ``GoodputMeter``) gets the trainer-identical attribution:
    sync saves and snapshot stalls tick ``checkpoint``; the flush wait —
    the background commit this caller blocks on only to time it — ticks
    ``checkpoint_async``.
    """
    best = {"sync_ms": float("inf"), "stall_ms": float("inf"), "commit_ms": None}
    for _ in range(repeats):
        t0 = time.perf_counter()
        manager.save("stall_sync", state, epoch=0)
        manager.wait()
        best["sync_ms"] = min(best["sync_ms"], (time.perf_counter() - t0) * 1e3)
        if meter is not None:
            meter.tick("checkpoint")
    with AsyncCheckpointSaver(manager) as saver:
        for _ in range(repeats):
            stall_s = saver.save_async("stall_async", state, epoch=0)
            if meter is not None:
                meter.tick("checkpoint")
            saver.flush()
            if meter is not None:
                meter.tick("checkpoint_async")
            best["stall_ms"] = min(best["stall_ms"], stall_s * 1e3)
            best["commit_ms"] = saver.last_commit_s * 1e3
    best["stall_ratio"] = best["stall_ms"] / max(best["sync_ms"], 1e-9)
    return best


class AsyncCheckpointSaver:
    """Decouple checkpoint saves from the training hot loop.

    ``manager`` should be a synchronous :class:`CheckpointManager`
    (``async_save=False``): the worker thread drives each save to a fully
    committed end state before picking the next, which is what makes the
    ordering and crash-window guarantees above hold. ``on_commit(name,
    seconds)`` runs on the worker thread after each successful commit (keep
    it cheap and thread-safe — the trainer uses it for goodput accounting
    and the commit event).

    ``commit_delay_s`` is a chaos/test seam: the worker sleeps that long in
    the ``committing`` state before touching the filesystem, widening the
    mid-background-commit crash window so ``scripts/chaos_soak.py`` can kill
    inside it deterministically. Production leaves it 0.
    """

    def __init__(
        self,
        manager,
        *,
        on_commit: Callable[[str, float], None] | None = None,
    ):
        self._manager = manager
        self._on_commit = on_commit
        self.commit_delay_s = 0.0
        # All queue/worker state below is guarded by _cond's lock.
        self._cond = threading.Condition()
        self._queue: list[SaveRequest] = []  # FIFO; one entry per name max
        self._current: SaveRequest | None = None  # the commit in flight
        self._error: BaseException | None = None
        self._stop = False
        self._thread: threading.Thread | None = None
        # Counters (read-only introspection; tests + chaos harness).
        self.committed = 0
        self.superseded = 0
        self.last_commit_s: float | None = None

    # -- public API --------------------------------------------------------

    def save_async(
        self,
        name: str,
        state: Any,
        epoch: int,
        *,
        metrics: Mapping | None = None,
        loop_state: Mapping | None = None,
        telemetry: Mapping | None = None,
        data_state: Mapping | None = None,
    ) -> float:
        """Snapshot ``state`` to host and queue its background commit.

        Returns the snapshot wall time in seconds — the only stall the hot
        loop pays. Raises a prior background commit's error (if any) before
        snapshotting: a failed save must surface on the training thread with
        the same fatality a failed synchronous save has, not vanish.
        """
        self._raise_pending_error()
        req = self._snapshot(
            name, state, epoch, metrics, loop_state, telemetry, data_state
        )
        with self._cond:
            self._ensure_worker()
            for i, queued in enumerate(self._queue):
                if queued.name == name:
                    # Newest-wins: the queued older snapshot of this name was
                    # never visible on disk; drop it in place (keeps FIFO
                    # position so distinct-name ordering is undisturbed).
                    queued.status = SUPERSEDED
                    self.superseded += 1
                    self._queue[i] = req
                    break
            else:
                self._queue.append(req)
            req.status = QUEUED
            self._cond.notify_all()
        return req.snapshot_s

    def save_sync(
        self,
        name: str,
        state: Any,
        epoch: int,
        *,
        metrics: Mapping | None = None,
        loop_state: Mapping | None = None,
        telemetry: Mapping | None = None,
        data_state: Mapping | None = None,
    ) -> float:
        """Emergency save: flush in-flight work, then commit synchronously.

        The SIGTERM / watchdog path. The flush *completes* (never abandons)
        a queued or committing save first — it may hold the only recent
        durable state, and interleaving two writers would break the
        single-committer invariant. A prior background commit's error is
        deferred, not raised (the emergency save itself must still run
        inside the grace window): it is re-stashed afterwards so the next
        ``flush``/``save_async`` surfaces it — a failed save never vanishes.
        The new save's own failure raises as usual. Returns wall seconds.
        """
        t0 = time.perf_counter()
        prior_err = self.flush(raise_errors=False)
        try:
            self._manager.save(
                name, state, epoch, metrics=metrics, loop_state=loop_state,
                telemetry=telemetry, data_state=data_state,
            )
            self._manager.wait()
        finally:
            # Re-stash even when the emergency save itself raises: the
            # earlier failure is the root cause and must still surface.
            if prior_err is not None:
                with self._cond:
                    if self._error is None:
                        self._error = prior_err
        return time.perf_counter() - t0

    def maybe_save_best(
        self,
        metrics: Mapping,
        state: Any,
        epoch: int,
        telemetry: Mapping | None = None,
        data_state: Mapping | None = None,
    ) -> tuple[bool, float]:
        """Async variant of ``CheckpointManager.maybe_save_best``: apply the
        best-fitness rule on-thread (host floats, free), snapshot + queue on
        improvement. Returns ``(saved, snapshot_seconds)``."""
        if not self._manager.best_improved(metrics):
            return False, 0.0
        stall = self.save_async(
            BEST, state, epoch, metrics=metrics, telemetry=telemetry,
            data_state=data_state,
        )
        return True, stall

    def flush(self, raise_errors: bool = True) -> BaseException | None:
        """Barrier: block until every queued save has fully committed (write
        finished AND atomically renamed). Surfaces (and clears) a background
        commit error — raised by default, returned when ``raise_errors`` is
        False (the emergency path logs instead of dying). Safe to call with
        no worker running."""
        with self._cond:
            while self._queue or self._current is not None:
                self._cond.wait(timeout=0.1)
        self._manager.wait()  # no-op for a sync manager; belt and braces
        with self._cond:
            err, self._error = self._error, None
        if err is not None and raise_errors:
            raise err
        return err

    @property
    def in_flight(self) -> bool:
        """True while any save is queued or committing."""
        with self._cond:
            return bool(self._queue) or self._current is not None

    def close(self) -> None:
        """Flush (errors returned, not raised) and stop the worker."""
        self.flush(raise_errors=False)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "AsyncCheckpointSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- internals ---------------------------------------------------------

    def _snapshot(
        self, name, state, epoch, metrics, loop_state, telemetry, data_state=None
    ):
        t0 = time.perf_counter()
        # The sharding-metadata record must come from the LIVE arrays —
        # device_get returns plain host numpy, and a record derived from the
        # snapshot would always be empty, silently dropping the layout from
        # every async save's meta while sync saves kept it.
        from distributed_training_pytorch_tpu.parallel.sharding import (
            sharding_record,
        )

        sharding = sharding_record(state)
        # device_get: one synchronous D2H copy into fresh host buffers. The
        # copy waits for the state's producing computation (so the snapshot
        # is consistent) but NOT for unrelated in-flight work, and later
        # train steps can donate/overwrite the device buffers freely — the
        # host copy is decoupled. For a SHARDED state each leaf is fetched
        # through its addressable shards (host-local rows of the global
        # array); typed PRNG keys come back as host-backed key arrays; the
        # manager's save path already serializes both.
        host_state = jax.device_get(state)
        req = SaveRequest(
            name,
            host_state,
            int(epoch),
            dict(
                metrics=metrics,
                loop_state=loop_state,
                telemetry=telemetry,
                sharding=sharding,
                # Host-side scalars captured at snapshot time (the reader's
                # position when the state snapshot was taken) — the data
                # plane's piece of the atomically-consistent save.
                data_state=data_state,
            ),
        )
        req.snapshot_s = time.perf_counter() - t0
        return req

    def _raise_pending_error(self) -> None:
        with self._cond:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _ensure_worker(self) -> None:
        # Called with _cond held.
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._worker, name="async-checkpoint-commit", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait()
                if self._stop and not self._queue:
                    return
                req = self._queue.pop(0)
                req.status = COMMITTING
                self._current = req
            try:
                if self.commit_delay_s:
                    time.sleep(self.commit_delay_s)  # chaos seam (see class doc)
                t0 = time.perf_counter()
                self._manager.save(req.name, req.state, req.epoch, **req.kwargs)
                self._manager.wait()  # sync manager: already committed; no-op
                req.commit_s = time.perf_counter() - t0
                # State-reporting counters are read from the training thread
                # (measure_save_stall, tests, the chaos soak) — publish them
                # under the same lock every other shared field uses, so a
                # reader never sees committed bumped with last_commit_s
                # still stale (jaxlint: cross-thread-mutation-without-lock).
                with self._cond:
                    req.status = COMMITTED
                    self.committed += 1
                    self.last_commit_s = req.commit_s
                if self._on_commit is not None:
                    try:
                        self._on_commit(req.name, req.commit_s)
                    except Exception:  # noqa: BLE001 — telemetry must not kill saves
                        pass
            except BaseException as e:  # noqa: BLE001 — surfaced on the main thread
                req.status = FAILED
                with self._cond:
                    # First unconsumed error wins (the root cause; a second
                    # failure before the next flush is usually the same
                    # disease) — never silently replace one failure with
                    # another.
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._current = None
                    self._cond.notify_all()
