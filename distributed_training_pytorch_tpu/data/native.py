"""ctypes bindings for the native data-loader runtime (``csrc/dtp_native.cpp``).

The reference's host-side image work runs in prebuilt native code (OpenCV,
``dataset/example_dataset.py:57-60``; albumentations SIMD) under torch
DataLoader workers. This module is the TPU build's native path: one GIL-free
C++ call per *batch* (decode+resize+normalize, CIFAR-style crop/flip/
normalize, or plain normalize), internally multithreaded, with Philox
randomness keyed identically to the Python pipeline
(``data/transforms.philox_key``) so results are deterministic across hosts.

The library is compiled on first use (``make -C csrc``) and cached next to
this file; everything degrades gracefully to the pure-Python path when a
toolchain isn't available — ``available()`` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

_LIB_NAME = "libdtp_native.so"
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "csrc")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load():
    """Load (building if necessary) the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        path = os.path.join(_LIB_DIR, _LIB_NAME)
        # (Re)build when the library is missing OR stale — an existing .so
        # older than any csrc source must not silently shadow edited code.
        needs_build = not os.path.exists(path)
        if not needs_build and os.path.isdir(_CSRC):
            # Only files the make target actually depends on — including the
            # Makefile here would make an edited Makefile trigger a perpetual
            # no-op `make` (its target depends on the .cpp alone).
            src_mtime = max(
                (
                    os.path.getmtime(os.path.join(_CSRC, f))
                    for f in os.listdir(_CSRC)
                    if f.endswith((".cpp", ".cc", ".h", ".hpp"))
                ),
                default=0.0,
            )
            needs_build = src_mtime > os.path.getmtime(path)
        if needs_build and os.path.isdir(_CSRC):
            try:
                subprocess.run(
                    ["make", "-C", _CSRC],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (subprocess.SubprocessError, OSError):
                if not os.path.exists(path):
                    _build_failed = True
                    return None
                # stale library + failed rebuild: better than nothing, but loud
                import warnings

                warnings.warn(
                    f"{_LIB_NAME} is older than csrc sources and rebuilding "
                    "failed; using the stale library"
                )
        if not os.path.exists(path):
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _build_failed = True
            return None
        i64, i32, u64 = ctypes.c_int64, ctypes.c_int, ctypes.c_uint64
        fptr = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        u8ptr = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64ptr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.dtp_decode_resize_normalize.restype = i64
        lib.dtp_decode_resize_normalize.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), i64, i32, i32, fptr, fptr, fptr, i32,
        ]
        lib.dtp_augment_crop_flip.restype = i64
        lib.dtp_augment_crop_flip.argtypes = [
            u8ptr, i64, i32, i32, i32, u64, u64, i64ptr, fptr, fptr, i32, fptr, i32,
        ]
        lib.dtp_normalize.restype = i64
        lib.dtp_normalize.argtypes = [u8ptr, i64, i32, i32, fptr, fptr, fptr, i32]
        lib.dtp_augment_crop_flip_u8.restype = i64
        lib.dtp_augment_crop_flip_u8.argtypes = [
            u8ptr, i64, i32, i32, i32, u64, u64, i64ptr, i32, u8ptr, i32,
        ]
        lib.dtp_decode_resize_normalize_bytes.restype = i64
        lib.dtp_decode_resize_normalize_bytes.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), i64ptr, i64, i32, i32, fptr, fptr, fptr, i32,
        ]
        lib.dtp_decode_resize_u8_bytes.restype = i64
        lib.dtp_decode_resize_u8_bytes.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), i64ptr, i64, i32, i32, u8ptr, i32,
        ]
        f32 = ctypes.c_float
        lib.dtp_decode_rrc_flip_u8_bytes.restype = i64
        lib.dtp_decode_rrc_flip_u8_bytes.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), i64ptr, i64, i32, i32, u64, u64,
            i64ptr, i32, f32, f32, f32, f32, u8ptr, i32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class DecodeError(ValueError):
    """A payload in a native decode batch failed; ``index`` is the position
    within the sequence passed to that call (callers slicing a larger batch
    remap it — see :func:`mixed_native_batch`)."""

    def __init__(self, index: int, what: str = "record payload"):
        self.index = index
        super().__init__(f"failed to decode {what} #{index}")


def _threads(n: int | None) -> int:
    return n if n is not None else min(16, os.cpu_count() or 1)


def decode_resize_normalize(
    paths: Sequence[str],
    height: int,
    width: int,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    threads: int | None = None,
) -> np.ndarray:
    """Decode JPEG/PNG files -> [N, H, W, 3] float32, resized (cv2-compatible
    bilinear) and normalized, in one native call."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(paths)
    out = np.empty((n, height, width, 3), np.float32)
    arr = (ctypes.c_char_p * n)(*[os.fsencode(p) for p in paths])
    rc = lib.dtp_decode_resize_normalize(
        arr, n, height, width,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        out, _threads(threads),
    )
    if rc:
        raise ValueError(f"failed to decode {paths[rc - 1]!r}")
    return out


def decode_resize_normalize_bytes(
    payloads: Sequence[bytes],
    height: int,
    width: int,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    threads: int | None = None,
) -> np.ndarray:
    """In-memory JPEG/PNG payloads (record-file shards) -> [N, H, W, 3]
    float32, resized + normalized in one native call (no temp files)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(payloads)
    lengths = np.asarray([len(p) for p in payloads], np.int64)
    # Zero-copy: c_char_p elements point straight at each bytes object's
    # buffer (lengths are passed explicitly; embedded NULs are fine).
    bufs = (ctypes.c_char_p * n)(*payloads)
    out = np.empty((n, height, width, 3), np.float32)
    rc = lib.dtp_decode_resize_normalize_bytes(
        bufs, lengths, n, height, width,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        out, _threads(threads),
    )
    if rc:
        raise DecodeError(rc - 1)
    return out


def decode_resize_u8_bytes(
    payloads: Sequence[bytes],
    height: int,
    width: int,
    *,
    threads: int | None = None,
) -> np.ndarray:
    """In-memory JPEG/PNG payloads -> [N, H, W, 3] uint8 (decode + resize, no
    normalize) — the ship-uint8 train path; pair with
    :func:`augment_crop_flip_u8` and on-device ``models.InputNormalizer``."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(payloads)
    lengths = np.asarray([len(p) for p in payloads], np.int64)
    bufs = (ctypes.c_char_p * n)(*payloads)
    out = np.empty((n, height, width, 3), np.uint8)
    rc = lib.dtp_decode_resize_u8_bytes(bufs, lengths, n, height, width, out, _threads(threads))
    if rc:
        raise DecodeError(rc - 1)
    return out


def decode_rrc_flip_u8_bytes(
    payloads: Sequence[bytes],
    height: int,
    width: int,
    indices: np.ndarray,
    *,
    seed: int,
    epoch: int,
    hflip: bool = True,
    scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3),
    threads: int | None = None,
) -> np.ndarray:
    """In-memory JPEG/PNG payloads -> [N, H, W, 3] uint8 via decode +
    RANDOM-RESIZED-CROP + optional hflip fused in one native call — the
    ImageNet train augmentation (10-attempt sampling with the repo's
    transforms.random_resized_crop center-square fallback; torchvision's
    fallback ratio-clamps instead), Philox-keyed per (seed, epoch,
    indices[i]). The
    full-size decode never crosses back into Python."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(payloads)
    lengths = np.asarray([len(p) for p in payloads], np.int64)
    bufs = (ctypes.c_char_p * n)(*payloads)
    out = np.empty((n, height, width, 3), np.uint8)
    rc = lib.dtp_decode_rrc_flip_u8_bytes(
        bufs, lengths, n, height, width, seed, epoch,
        np.ascontiguousarray(indices, np.int64), int(hflip),
        float(scale[0]), float(scale[1]), float(ratio[0]), float(ratio[1]),
        out, _threads(threads),
    )
    if rc:
        raise DecodeError(rc - 1)
    return out


def mixed_native_batch(
    n, height, width, native_positions, native_fn, py_fn, *, dtype=np.float32
) -> np.ndarray:
    """Assemble a decoded batch where some rows take the native batch call and
    the rest fall back per record (shared by the folder and record sources).

    ``native_positions``: batch positions decodable natively (position-based —
    row indices can repeat under pad_final). ``native_fn(positions)`` returns
    the stacked native results for those positions; ``py_fn(position)`` one
    fallback row.
    """
    images = np.empty((n, height, width, 3), dtype)
    if native_positions:
        try:
            images[native_positions] = native_fn(native_positions)
        except DecodeError as e:
            # remap the subset-relative index to the batch position, so the
            # error names the record an operator would actually look for
            raise DecodeError(native_positions[e.index], "batch record") from None
    for p in set(range(n)) - set(native_positions):
        images[p] = py_fn(p)
    return images


def augment_crop_flip(
    images: np.ndarray,
    indices: np.ndarray,
    *,
    pad: int,
    seed: int,
    epoch: int,
    mean: np.ndarray,
    std: np.ndarray,
    hflip: bool = True,
    threads: int | None = None,
) -> np.ndarray:
    """Deterministic reflect-pad/random-crop/hflip/normalize over a uint8
    NHWC batch. Randomness keyed per record by (seed, epoch, indices[i])."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    assert c == 3
    out = np.empty((n, h, w, 3), np.float32)
    lib.dtp_augment_crop_flip(
        images, n, h, w, pad, seed, epoch,
        np.ascontiguousarray(indices, np.int64),
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        int(hflip), out, _threads(threads),
    )
    return out


def augment_crop_flip_u8(
    images: np.ndarray,
    indices: np.ndarray,
    *,
    pad: int,
    seed: int,
    epoch: int,
    hflip: bool = True,
    threads: int | None = None,
) -> np.ndarray:
    """Crop/flip only, uint8 -> uint8 (same Philox stream as
    :func:`augment_crop_flip`). For device-side normalization: ship 1 byte
    per pixel over the host->device link instead of 4."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    assert c == 3
    out = np.empty((n, h, w, 3), np.uint8)
    lib.dtp_augment_crop_flip_u8(
        images, n, h, w, pad, seed, epoch,
        np.ascontiguousarray(indices, np.int64),
        int(hflip), out, _threads(threads),
    )
    return out


class NativeCropFlipU8:
    """Batch transform that keeps images uint8 (crop/flip only); pair with
    on-device normalization (``models.InputNormalizer``) so the H2D link
    carries 4x fewer bytes and XLA fuses the normalize into the first conv."""

    def __init__(self, *, pad: int = 4, seed: int = 0, train: bool = True):
        self.pad = pad
        self.seed = seed
        self.train = train

    def batch_apply(self, images: np.ndarray, indices: np.ndarray, epoch: int) -> np.ndarray:
        if not self.train:
            return np.ascontiguousarray(images, np.uint8)
        return augment_crop_flip_u8(
            images, np.asarray(indices, np.int64),
            pad=self.pad, seed=self.seed, epoch=epoch,
        )

    def __call__(self, img: np.ndarray, *, epoch: int = 0, index: int = 0) -> np.ndarray:
        return self.batch_apply(img[None], np.array([index]), epoch)[0]


class NativeCropFlipNormalize:
    """Batch transform (loader ``batch_apply`` protocol): reflect-pad-``pad``
    random crop + horizontal flip + normalize over uint8 NHWC batches, one
    native call per batch. ``train=False`` skips the random ops (val path).

    Randomness is keyed by (seed, epoch, record index) like the Python
    pipeline; the two paths draw differently from Philox, so each is
    deterministic and host-consistent but they are not bit-identical to each
    other."""

    def __init__(self, mean, std, *, pad: int = 4, seed: int = 0, train: bool = True):
        self.mean = np.ascontiguousarray(mean, np.float32)
        self.std = np.ascontiguousarray(std, np.float32)
        self.pad = pad
        self.seed = seed
        self.train = train

    def batch_apply(self, images: np.ndarray, indices: np.ndarray, epoch: int) -> np.ndarray:
        if not self.train:
            return normalize(images, self.mean, self.std)
        return augment_crop_flip(
            images,
            np.asarray(indices, np.int64),
            pad=self.pad,
            seed=self.seed,
            epoch=epoch,
            mean=self.mean,
            std=self.std,
        )

    def __call__(self, img: np.ndarray, *, epoch: int = 0, index: int = 0) -> np.ndarray:
        """Single-record fallback (loader Python path)."""
        return self.batch_apply(img[None], np.array([index]), epoch)[0]


def normalize(
    images: np.ndarray,
    mean: np.ndarray,
    std: np.ndarray,
    *,
    threads: int | None = None,
) -> np.ndarray:
    """uint8 NHWC -> normalized float32, one native call."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    images = np.ascontiguousarray(images, np.uint8)
    n, h, w, c = images.shape
    assert c == 3
    out = np.empty((n, h, w, 3), np.float32)
    lib.dtp_normalize(
        images, n, h, w,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        out, _threads(threads),
    )
    return out
