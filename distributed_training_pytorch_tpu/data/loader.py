"""Host-sharded batch loader with deterministic global shuffle.

Capability twin of ``DataLoader`` + ``DistributedSampler``
(``trainer/trainer.py:209-217``): global-batch semantics (the user specifies
the *global* batch size, split across hosts — ``trainer/trainer.py:56``),
per-epoch reshuffle via ``set_epoch`` (``:140``), and parallel host-side
loading (``num_workers``, ``:213``).

TPU-first differences:

* the shuffle permutation is seeded by ``(seed, epoch)`` and computed
  identically on every host (fixes the reference's cross-rank shuffle bug,
  SURVEY.md §2e) — host ``p`` takes rows ``[p*L, (p+1)*L)`` of each global
  batch, ``L = global_batch // process_count``;
* batches have **static shape**: training drops the trailing partial batch
  (XLA recompiles on shape change); eval pads the final batch and emits a
  ``"mask"`` weight column so padded rows don't pollute metrics;
* workers are threads, not processes — cv2/numpy release the GIL, and thread
  workers share the page cache with zero pickling overhead.
"""

from __future__ import annotations

import concurrent.futures as cf
import queue
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from distributed_training_pytorch_tpu.data import transforms


class ShardedLoader:
    """Iterate host-local batches ``{field: np.ndarray}`` over a data source.

    ``transform(image, epoch=, index=)`` is applied to the ``"image"`` field of
    each record when provided (a :class:`~.transforms.Compose`).
    """

    def __init__(
        self,
        source,
        global_batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        transform: Optional[Callable] = None,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 8,
        prefetch_batches: int = 2,
        drop_last: bool = True,
        pad_final: bool = False,
        process_index: int | None = None,
        process_count: int | None = None,
        skip_corrupt: bool = False,
    ):
        if drop_last and pad_final:
            raise ValueError("drop_last and pad_final are mutually exclusive")
        self.source = source
        # Ref-parity extension point: the reference ctor forwards
        # ``dataset.collate_fn`` to DataLoader (``trainer/trainer.py:59-71``).
        # A collate takes the list of transformed records and returns the
        # batch dict — required when records carry ragged/non-stackable
        # fields. Explicit arg wins; else the source's attribute; else the
        # default field-wise np.stack.
        self.collate_fn = collate_fn if collate_fn is not None else getattr(
            source, "collate_fn", None
        )
        # Same fallback for the transform: sources carry their transform as an
        # attribute (applied by the loader, not __getitem__, so augmentation
        # keys on (epoch, index)); a direct ShardedLoader(source) construction
        # must not silently drop it — un-normalized eval images cost measured
        # accuracy (digits run: 98.3% vs the true 99.4%) while looking fine.
        if transform is None:
            transform = getattr(source, "transform", None)
        self.global_batch_size = int(global_batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.transform = transform
        self.num_workers = int(num_workers)
        # Host-side look-ahead window: how many *batches* may be in flight
        # (decoding/augmenting) beyond the one being consumed. Distinct from
        # the device-side ``device_prefetch(depth=2)`` ring downstream of the
        # loader (utils/tpu.py): this knob bounds host RAM (window x batch
        # bytes) and decode overlap; that one bounds on-device staging. The
        # defaults compose: 2 host batches decoding while 2 sit on device.
        self.prefetch_batches = max(1, int(prefetch_batches))
        self.drop_last = drop_last
        self.pad_final = pad_final
        # Graceful degradation: a corrupt record (CorruptRecordError, or a
        # decode ValueError) is deterministically replaced by the next
        # readable one and counted in ``corrupt_skipped`` instead of failing
        # the epoch. Sources with their own tolerant batch path (records.py
        # ``skip_corrupt``) get the flag forwarded so the whole-batch fast
        # path degrades the same way — note this SETS the attribute on the
        # caller's source object: don't share one source between a tolerant
        # loader and a strict reader (build a second source over the same
        # shards instead; the footer-index read is cheap).
        self.skip_corrupt = bool(skip_corrupt)
        self._corrupt_skipped = 0
        # Injection seam (ISSUE 13; the FaultPlan/commit_delay_s pattern for
        # the input pipeline): sleep this long in every batch's production
        # path, on the producing thread — a deterministic way to make the
        # loader the bottleneck so the telemetry `data_wait` bucket, the
        # perf gate's data_wait ceiling (scripts/perf_gate.py --data-wait
        # --inject-data-wait), and the run doctor's data_bound verdict can
        # be self-tested against a KNOWN starved pipeline. Production
        # leaves it 0; settable post-construction (loader.load_delay_s=...).
        self.load_delay_s = 0.0
        if skip_corrupt and hasattr(source, "skip_corrupt"):
            source.skip_corrupt = True
        self._epoch = 0
        self._pidx = jax.process_index() if process_index is None else process_index
        self._pcount = jax.process_count() if process_count is None else process_count
        if self.global_batch_size % self._pcount:
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"{self._pcount} processes"
            )
        self.local_batch_size = self.global_batch_size // self._pcount

    @property
    def corrupt_skipped(self) -> int:
        """Total records skipped as corrupt — loader-level substitutions
        (decode/transform failures) PLUS the source's own tolerant-read count
        (structural corruption handled inside batch fast paths), so callers
        see one number regardless of which layer degraded."""
        return self._corrupt_skipped + int(getattr(self.source, "corrupt_skipped", 0))

    def set_epoch(self, epoch: int) -> None:
        """Reseed the epoch permutation — ``sampler.set_epoch`` analog
        (``trainer/trainer.py:140``)."""
        self._epoch = int(epoch)

    def __len__(self) -> int:
        n = len(self.source)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def _global_order(self) -> np.ndarray:
        n = len(self.source)
        if self.shuffle:
            rng = np.random.Generator(
                np.random.Philox(
                    key=transforms.philox_key(self.seed, self._epoch, transforms.SHUFFLE_INDEX)
                )
            )
            return rng.permutation(n)
        return np.arange(n)

    def _load_one_raw(self, index: int, epoch: int) -> dict:
        record = dict(self.source[int(index)])
        if self.transform is not None and "image" in record:
            record["image"] = self.transform(record["image"], epoch=epoch, index=int(index))
        return record

    def _load_one(self, index: int, epoch: int) -> dict:
        if not self.skip_corrupt:
            return self._load_one_raw(index, epoch)
        from distributed_training_pytorch_tpu.data.records import (
            _SKIP_COUNT_LOCK,
            CorruptRecordError,
            tolerant_fetch,
        )

        record, skipped = tolerant_fetch(
            lambda i: self._load_one_raw(i, epoch),
            index,
            len(self.source),
            # decode/transform failures raise plain ValueError too
            exceptions=(CorruptRecordError, ValueError),
        )
        if skipped:
            with _SKIP_COUNT_LOCK:  # worker threads bump this concurrently
                self._corrupt_skipped += skipped
        return record

    def _batch_fast_path(self):
        """Whole-batch production in one call (native C++ runtime): either the
        source loads batches itself (``load_batch``), or it exposes in-memory
        ``arrays`` and the transform is batch-capable (``batch_apply``)."""
        if self.collate_fn is not None:
            # Custom collate implies per-record production — the batch fast
            # paths stack fields themselves, which is exactly what a custom
            # collate exists to replace.
            return None
        if hasattr(self.source, "load_batch"):
            return "source"
        if (
            self.transform is not None
            and hasattr(self.transform, "batch_apply")
            and hasattr(self.source, "arrays")
        ):
            return "arrays"
        return None

    def _maybe_delay(self) -> None:
        if self.load_delay_s:
            import time

            time.sleep(float(self.load_delay_s))  # injection seam (see ctor)

    def _produce_batch(self, rows: np.ndarray, mask, epoch: int, fast: str | None) -> dict:
        self._maybe_delay()
        if fast == "source":
            batch = dict(self.source.load_batch(rows, epoch))
        elif fast == "arrays":
            batch = {k: v[rows] for k, v in self.source.arrays.items()}
            if "image" in batch:
                batch["image"] = self.transform.batch_apply(batch["image"], rows, epoch)
        else:
            records = [self._load_one(i, epoch) for i in rows]
            return self._collate(records, mask)
        if mask is not None:
            batch["mask"] = mask
        return batch

    def _collate(self, records: list[dict], mask: np.ndarray | None) -> dict:
        if self.collate_fn is not None:
            batch = dict(self.collate_fn(records))
        else:
            batch = {k: np.stack([r[k] for r in records]) for k in records[0]}
        if mask is not None:
            # The pad mask stays loader-owned even under a custom collate:
            # padded-row weighting is a loader invariant, not a collate concern.
            batch["mask"] = mask
        return batch

    def global_real_count(self, batch_index: int) -> int:
        """Real (unpadded) rows in global batch ``batch_index`` — identical on
        every host; the correct cross-batch aggregation weight for padded
        validation (each host's local mask sum differs, this does not)."""
        n = len(self.source)
        return max(0, min(self.global_batch_size, n - batch_index * self.global_batch_size))

    def __iter__(self) -> Iterator[dict]:
        return self.iter_batches(0)

    def iter_batches(self, start: int = 0) -> Iterator[dict]:
        """Iterate host-local batches from global batch ``start`` onward.

        ``start > 0`` is the mid-epoch RESUME path: the permutation is a pure
        function of ``(seed, epoch)``, so skipping happens at the index level
        — none of the skipped batches' records are read, decoded, or
        augmented (draining a generator instead would pay the full host
        pipeline for every discarded batch)."""
        order = self._global_order()
        epoch = self._epoch
        num_batches = len(self)
        G = self.global_batch_size
        L = self.local_batch_size

        def batch_indices(b: int) -> tuple[np.ndarray, np.ndarray | None]:
            """This host's row indices for global batch b, plus its slice of
            the global pad mask (None when the loader doesn't pad).

            The final partial batch is padded at the *global* level (repeat
            the last real row up to G) and then sliced per host — every host
            always produces exactly L rows, and the mask is globally
            consistent regardless of how real rows land across hosts."""
            rows = order[b * G : (b + 1) * G]
            mask = None
            if self.pad_final:
                real = len(rows)
                if real < G:
                    rows = np.concatenate([rows, np.repeat(rows[-1:], G - real)])
                mask = (np.arange(G) < real).astype(np.float32)
                mask = mask[self._pidx * L : (self._pidx + 1) * L]
            return rows[self._pidx * L : (self._pidx + 1) * L], mask

        fast = self._batch_fast_path()
        start = max(0, int(start))

        if self.num_workers <= 0:
            for b in range(start, num_batches):
                rows, mask = batch_indices(b)
                yield self._produce_batch(rows, mask, epoch, fast)
            return

        # Thread pool with a bounded in-flight window so decode/augment of
        # batch b+1..b+2 overlaps consumption of batch b. Fast-path batches
        # are one future each (the native call is internally multithreaded
        # and GIL-free); the Python path fans out per record.
        with cf.ThreadPoolExecutor(self.num_workers) as pool:
            window: queue.Queue = queue.Queue()
            ahead = self.prefetch_batches

            def submit(b: int):
                rows, mask = batch_indices(b)
                if fast is not None:
                    window.put(
                        (pool.submit(self._produce_batch, rows, mask, epoch, fast), None)
                    )
                else:
                    futs = [pool.submit(self._load_one, i, epoch) for i in rows]
                    window.put((futs, mask))

            upto = min(start + ahead, num_batches)
            for b in range(start, upto):
                submit(b)
            for _ in range(num_batches - start):
                item, mask = window.get()
                if upto < num_batches:
                    submit(upto)
                    upto += 1
                if fast is not None:
                    yield item.result()
                else:
                    self._maybe_delay()  # per-record path: delay at collate
                    yield self._collate([f.result() for f in item], mask)
