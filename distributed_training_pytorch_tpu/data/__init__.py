from distributed_training_pytorch_tpu.data.dataset import (  # noqa: F401
    ArrayDataSource,
    ImageFolderDataSource,
    NativeImageFolderSource,
)
from distributed_training_pytorch_tpu.data import native  # noqa: F401
from distributed_training_pytorch_tpu.data.loader import ShardedLoader  # noqa: F401
from distributed_training_pytorch_tpu.data.records import (  # noqa: F401
    NativeRecordFileSource,
    NativeRecordTrainSource,
    RecordFileSource,
    RecordFileWriter,
    pack_image_folder,
    write_shards,
)
from distributed_training_pytorch_tpu.data.streaming import (  # noqa: F401
    DecodePool,
    ReaderState,
    StreamingLoader,
    shard_array_source,
)
from distributed_training_pytorch_tpu.data.prefetch import (  # noqa: F401
    device_prefetch,
    device_prefetch_chained,
)
from distributed_training_pytorch_tpu.data.transforms import (  # noqa: F401
    IMAGENET_MEAN,
    IMAGENET_STD,
    eval_transform,
    train_transform,
)
