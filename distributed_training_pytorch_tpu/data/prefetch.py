"""Device prefetch: overlap host batch production with device compute.

The reference's per-step H2D copy is synchronous inside ``train_step``
(``example_trainer.py:70,75`` — and never overlapped despite ``pin_memory``,
SURVEY.md §2e). Here transfers are issued from a background thread ``depth``
batches ahead: ``jax.make_array_from_process_local_data`` starts the async
H2D copy and XLA's scheduler overlaps it with the running step.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

import jax

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib


def device_prefetch(
    batches: Iterable[dict],
    mesh: jax.sharding.Mesh,
    *,
    depth: int = 2,
) -> Iterator[dict]:
    """Yield global data-sharded ``jax.Array`` batches, ``depth`` in flight."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    _SENTINEL = object()
    err: list[BaseException] = []
    cancelled = threading.Event()

    def producer():
        try:
            for host_batch in batches:
                item = mesh_lib.global_array_from_host_local(host_batch, mesh)
                # Bounded put that aborts when the consumer goes away, so an
                # abandoned iterator can't leave this thread (and `depth`
                # device batches) parked on a full queue forever.
                while not cancelled.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            while True:  # sentinel put must not block either
                try:
                    q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    if cancelled.is_set():
                        break
    thread = threading.Thread(target=producer, daemon=True, name="device-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        cancelled.set()
        while not q.empty():  # release device buffers held by the queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        thread.join(timeout=2.0)
