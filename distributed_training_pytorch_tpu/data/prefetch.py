"""Device prefetch: overlap host batch production with device compute.

The reference's per-step H2D copy is synchronous inside ``train_step``
(``example_trainer.py:70,75`` — and never overlapped despite ``pin_memory``,
SURVEY.md §2e). Here transfers are issued from a background thread ``depth``
batches ahead: ``jax.make_array_from_process_local_data`` starts the async
H2D copy and XLA's scheduler overlaps it with the running step.

Two staging modes share the same producer/consumer machinery:

* :func:`device_prefetch` — one global batch per item (the single-step loop);
* :func:`device_prefetch_chained` — chain-major: ``chain_steps`` consecutive
  global batches stacked on a new leading axis and shipped as ONE device
  array per window (``parallel.mesh.chain_batch_sharding`` layout), feeding
  the engine's chained train step. Still ``depth`` *windows* in flight, so
  on-device staging memory is bounded by ``depth x chain_steps`` batches.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, Iterator

import jax
import numpy as np

from distributed_training_pytorch_tpu.parallel import mesh as mesh_lib


def _prefetched(items: Iterable, depth: int) -> Iterator:
    """Drive ``items`` from a background thread, ``depth`` results in flight.

    Shutdown contract (both normal exhaustion and an abandoned consumer): the
    producer's ``put`` aborts once ``cancelled`` is set, and the consumer's
    cleanup must release every device buffer parked in the queue. The drain
    below runs *after* signalling ``cancelled``, pulls with ``get_nowait``
    until ``Empty`` (``q.empty()`` is only a snapshot — a producer blocked in
    ``q.put`` can land one more item right after a non-empty check), and
    re-drains once more after ``join``: the producer may have completed a
    final ``put`` between the first drain and its own ``cancelled`` check, and
    a buffer stranded that way would keep ``depth`` device batches live for
    the queue object's lifetime.
    """
    q: queue.Queue = queue.Queue(maxsize=depth)
    _SENTINEL = object()
    err: list[BaseException] = []
    cancelled = threading.Event()

    def producer():
        try:
            for item in items:
                # Bounded put that aborts when the consumer goes away, so an
                # abandoned iterator can't leave this thread (and `depth`
                # device batches) parked on a full queue forever.
                while not cancelled.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if cancelled.is_set():
                    return
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            while True:  # sentinel put must not block either
                try:
                    q.put(_SENTINEL, timeout=0.1)
                    break
                except queue.Full:
                    if cancelled.is_set():
                        break

    thread = threading.Thread(target=producer, daemon=True, name="device-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        cancelled.set()

        def drain():
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    return

        drain()
        thread.join(timeout=2.0)
        drain()  # a put completed before the producer observed `cancelled`


def device_prefetch(
    batches: Iterable[dict],
    mesh: jax.sharding.Mesh,
    *,
    depth: int = 2,
) -> Iterator[dict]:
    """Yield global data-sharded ``jax.Array`` batches, ``depth`` in flight."""
    staged = (
        mesh_lib.global_array_from_host_local(host_batch, mesh)
        for host_batch in batches
    )
    return _prefetched(staged, depth)


def device_prefetch_chained(
    batches: Iterable[dict],
    mesh: jax.sharding.Mesh,
    chain_steps: int,
    *,
    depth: int = 2,
    lead_singles: int = 0,
) -> Iterator[tuple[int, dict]]:
    """Chain-major device staging: yield ``(n, batch)`` execution units.

    ``n == chain_steps``: ``batch`` is a window of ``chain_steps`` consecutive
    global batches stacked on a new leading axis (one
    ``chain_batch_sharding``-laid-out transfer), ready for
    ``TrainEngine.train_steps_chained``. ``n == 1``: ``batch`` is a plain
    single-step global batch — emitted for the first ``lead_singles`` batches
    (the trainer's window-boundary realignment after a mid-epoch resume, and
    its profiled first-epoch prefix) and for the epoch tail shorter than a
    full window (compiling a fresh chain per tail length would cost a
    full-model retrace; the tail reuses the already-compiled single step).

    ``chain_steps == 1`` degenerates to :func:`device_prefetch` semantics
    (every unit a single), so one consumer loop serves both modes.
    """
    if chain_steps < 1:
        raise ValueError(f"chain_steps must be >= 1, got {chain_steps}")

    def staged():
        it = iter(batches)
        for host_batch in itertools.islice(it, max(0, int(lead_singles))):
            yield 1, mesh_lib.global_array_from_host_local(host_batch, mesh)
        while True:
            window = list(itertools.islice(it, chain_steps))
            if not window:
                return
            if len(window) < chain_steps or chain_steps == 1:
                for host_batch in window:
                    yield 1, mesh_lib.global_array_from_host_local(host_batch, mesh)
                if len(window) < chain_steps:
                    return
                continue
            stacked = jax.tree.map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *window
            )
            yield chain_steps, mesh_lib.global_chain_array_from_host_local(stacked, mesh)

    return _prefetched(staged(), depth)
