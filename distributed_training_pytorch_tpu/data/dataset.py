"""Data sources: indexable record stores the loader shards across hosts.

Capability twin of the reference ``dataset/example_dataset.py``: an
image-folder dataset that scans ``<root>/<label>/`` directories into
``(path, label_index)`` records (``dataset/example_dataset.py:24-30``) and
decodes images BGR->RGB via cv2 (``:57-60``).

Deliberate fix (SURVEY.md §2e): the reference shuffles the record list with an
*unseeded* ``random.shuffle`` in the constructor (``:17``), giving every rank
a different order under a sampler that assumes identical order. Here the scan
order is deterministic (sorted) and all shuffling happens in the loader,
seeded identically on every host.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


class ImageFolderDataSource:
    """Records = sorted files under ``<data_path>/<label>/`` per label.

    ``labels`` maps directory name -> class index by position, the contract of
    ``dataset/example_dataset.py:12,26-28`` (``labels.index(label)``).
    """

    def __init__(self, data_path: str, labels: Sequence[str], transform=None):
        self.data_path = data_path
        self.labels = list(labels)
        # Applied by the loader (not __getitem__) so augmentation can be keyed
        # by (epoch, record index) for determinism — see loader.ShardedLoader.
        self.transform = transform
        self.records: list[tuple[str, int]] = []
        for idx, label in enumerate(self.labels):
            label_dir = os.path.join(data_path, label)
            if not os.path.isdir(label_dir):
                raise FileNotFoundError(f"label directory missing: {label_dir}")
            for fname in sorted(os.listdir(label_dir)):
                if fname.lower().endswith(_IMAGE_EXTS):
                    self.records.append((os.path.join(label_dir, fname), idx))
        if not self.records:
            raise ValueError(f"no images found under {data_path} for labels {labels}")

    def __len__(self) -> int:
        return len(self.records)

    def __getitem__(self, index: int) -> dict:
        path, label = self.records[index]
        return {"image": _decode_image(path), "label": np.int32(label)}


def _decode_image(path: str) -> np.ndarray:
    """Decode to RGB uint8 HWC. cv2 reads BGR; flip to RGB — the exact
    behavior of ``dataset/example_dataset.py:57-60``. Falls back to PIL."""
    try:
        import cv2

        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError(f"cv2 failed to decode {path}")
        return img[:, :, ::-1]  # BGR -> RGB
    except ImportError:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))


class NativeImageFolderSource(ImageFolderDataSource):
    """Image-folder source whose batches decode/resize/normalize in one call
    into the native C++ runtime (``data/native.py``) — the no-augmentation
    (val/eval) hot path. Falls back to the per-record Python transform path
    inside ``load_batch`` when the native library is unavailable."""

    # formats the csrc decoders handle; anything else (bmp/webp from
    # _IMAGE_EXTS) falls back to the per-record cv2/PIL path below.
    _NATIVE_EXTS = (".jpg", ".jpeg", ".png")

    def __init__(
        self,
        data_path: str,
        labels: Sequence[str],
        height: int,
        width: int,
        mean=None,
        std=None,
    ):
        super().__init__(data_path, labels, transform=None)
        from distributed_training_pytorch_tpu.data import native, transforms

        self.height, self.width = height, width
        self.mean = transforms.IMAGENET_MEAN if mean is None else np.asarray(mean, np.float32)
        self.std = transforms.IMAGENET_STD if std is None else np.asarray(std, np.float32)
        self._native = native if native.available() else None
        # Python fallback must use the SAME mean/std as the native call, or a
        # mixed jpeg+bmp batch gets inconsistent normalization.
        self._py_transform = transforms.Compose(
            [transforms.resize(height, width), transforms.normalize(self.mean, self.std)]
        )
        if self._native is None:
            self.transform = self._py_transform

    def _decode_py(self, index: int) -> np.ndarray:
        return self._py_transform(super().__getitem__(index)["image"])

    def load_batch(self, rows: np.ndarray, epoch: int) -> dict:
        labels = np.array([self.records[int(i)][1] for i in rows], np.int32)
        if self._native is not None:
            from distributed_training_pytorch_tpu.data.native import mixed_native_batch

            images = mixed_native_batch(
                len(rows),
                self.height,
                self.width,
                # Partition by POSITION (row indices repeat under pad_final).
                [
                    p
                    for p, i in enumerate(rows)
                    if self.records[int(i)][0].lower().endswith(self._NATIVE_EXTS)
                ],
                lambda pos: self._native.decode_resize_normalize(
                    [self.records[int(rows[p])][0] for p in pos],
                    self.height,
                    self.width,
                    self.mean,
                    self.std,
                ),
                lambda p: self._decode_py(int(rows[p])),
            )
        else:
            images = np.stack([self._decode_py(int(i)) for i in rows])
        return {"image": images, "label": labels}


class ArrayDataSource:
    """In-memory source over parallel arrays — the synthetic-data path used by
    tests and benchmarks (SURVEY.md §7 'minimum end-to-end slice')."""

    def __init__(self, transform=None, **arrays: np.ndarray):
        self.transform = transform
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"array lengths differ: {lengths}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self._len = next(iter(lengths.values()))

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, index: int) -> dict:
        return {k: v[index] for k, v in self.arrays.items()}
