"""Host-side image transforms with deterministic per-record randomness.

Capability twin of the reference augmentation pipeline
(``dataset/example_dataset.py:35-50``): train phase = Resize, RandomRotate90,
HorizontalFlip, VerticalFlip, Blur, MedianBlur, CLAHE,
RandomBrightnessContrast, RandomGamma, ImageCompression (each p=0.5),
ImageNet-mean Normalize; val phase = Resize + Normalize only.

Design differences (TPU-first, SURVEY.md §2e/§7):

* randomness is a counter-based ``np.random.Philox`` keyed by
  ``(seed, epoch, record_index)`` — every host computes identical augmentation
  for the same record, and resume replays the same epoch stream (the
  reference's augmentations are unseeded process-global RNG);
* output is float32 **HWC** (batched to NHWC, XLA:TPU's native conv layout)
  rather than ToTensorV2's CHW (``:45``);
* augmentation runs in loader worker threads on the host — TPU never sees it.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

# A Transform maps (rgb uint8 HWC image, np.random.Generator) -> image.
Transform = Callable[[np.ndarray, np.random.Generator], np.ndarray]


def philox_key(seed: int, epoch: int, index: int) -> np.ndarray:
    """Pack (seed, epoch, index) into Philox's 2x64-bit key (epoch in the top
    24 bits of word 1, index below — supports 2^40-1 records per epoch; the
    top index value is reserved, see ``SHUFFLE_INDEX``)."""
    word1 = (np.uint64(epoch) << np.uint64(40)) | np.uint64(index)
    return np.array([np.uint64(seed), word1], dtype=np.uint64)


# Reserved record-index for the loader's epoch-shuffle stream: domain-separates
# the permutation draws from every per-record augmentation stream (record 0's
# key would otherwise equal the shuffle key for the same (seed, epoch)).
SHUFFLE_INDEX = (1 << 40) - 1


def _cv2():
    import cv2

    return cv2


def resize(height: int, width: int) -> Transform:
    def apply(img, rng):
        cv2 = _cv2()
        return cv2.resize(img, (width, height), interpolation=cv2.INTER_LINEAR)

    return apply


def random_resized_crop(
    height: int,
    width: int,
    scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3),
) -> Transform:
    """Standard ImageNet train crop: sample an area fraction and aspect ratio,
    crop, resize to (height, width). Falls back to a center crop when 10
    attempts don't fit (torchvision semantics)."""

    def apply(img, rng):
        cv2 = _cv2()
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * rng.uniform(*scale)
            log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
            cw = int(round(np.sqrt(target * np.exp(log_r))))
            ch = int(round(np.sqrt(target / np.exp(log_r))))
            if 0 < cw <= w and 0 < ch <= h:
                y0 = int(rng.integers(0, h - ch + 1))
                x0 = int(rng.integers(0, w - cw + 1))
                crop = img[y0 : y0 + ch, x0 : x0 + cw]
                return cv2.resize(crop, (width, height), interpolation=cv2.INTER_LINEAR)
        side = min(h, w)
        y0, x0 = (h - side) // 2, (w - side) // 2
        crop = img[y0 : y0 + side, x0 : x0 + side]
        return cv2.resize(crop, (width, height), interpolation=cv2.INTER_LINEAR)

    return apply


def random_rotate90(p: float = 0.5) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            img = np.rot90(img, k=int(rng.integers(1, 4)))
        return img

    return apply


def horizontal_flip(p: float = 0.5) -> Transform:
    def apply(img, rng):
        return img[:, ::-1] if rng.random() < p else img

    return apply


def vertical_flip(p: float = 0.5) -> Transform:
    def apply(img, rng):
        return img[::-1] if rng.random() < p else img

    return apply


def blur(p: float = 0.5, max_kernel: int = 7) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            k = int(rng.integers(1, max_kernel // 2 + 1)) * 2 + 1  # odd, 3..7
            img = _cv2().blur(np.ascontiguousarray(img), (k, k))
        return img

    return apply


def median_blur(p: float = 0.5, max_kernel: int = 5) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            k = int(rng.integers(1, max_kernel // 2 + 1)) * 2 + 1  # odd, 3..5
            img = _cv2().medianBlur(np.ascontiguousarray(img), k)
        return img

    return apply


def clahe(p: float = 0.5, clip_limit: float = 4.0, tile: int = 8) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            cv2 = _cv2()
            lab = cv2.cvtColor(np.ascontiguousarray(img), cv2.COLOR_RGB2LAB)
            op = cv2.createCLAHE(clipLimit=clip_limit, tileGridSize=(tile, tile))
            lab[:, :, 0] = op.apply(lab[:, :, 0])
            img = cv2.cvtColor(lab, cv2.COLOR_LAB2RGB)
        return img

    return apply


def random_brightness_contrast(p: float = 0.5, limit: float = 0.2) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            alpha = 1.0 + float(rng.uniform(-limit, limit))  # contrast
            beta = float(rng.uniform(-limit, limit)) * 255.0  # brightness
            img = np.clip(img.astype(np.float32) * alpha + beta, 0, 255).astype(np.uint8)
        return img

    return apply


def random_gamma(p: float = 0.5, gamma_range: tuple[int, int] = (80, 120)) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            gamma = float(rng.uniform(*gamma_range)) / 100.0
            img = (np.power(img.astype(np.float32) / 255.0, gamma) * 255.0).astype(np.uint8)
        return img

    return apply


def image_compression(p: float = 0.5, quality_range: tuple[int, int] = (80, 100)) -> Transform:
    def apply(img, rng):
        if rng.random() < p:
            cv2 = _cv2()
            quality = int(rng.integers(quality_range[0], quality_range[1] + 1))
            ok, enc = cv2.imencode(
                ".jpg",
                np.ascontiguousarray(img[:, :, ::-1]),
                [int(cv2.IMWRITE_JPEG_QUALITY), quality],
            )
            if ok:
                img = cv2.imdecode(enc, cv2.IMREAD_COLOR)[:, :, ::-1]
        return img

    return apply


def normalize(mean: np.ndarray = IMAGENET_MEAN, std: np.ndarray = IMAGENET_STD) -> Transform:
    def apply(img, rng):
        return (img.astype(np.float32) / 255.0 - mean) / std

    return apply


class Compose:
    """Apply transforms in order with a Philox generator keyed by
    ``(seed, epoch, index)`` — deterministic and host-independent."""

    def __init__(self, transforms: Sequence[Transform], seed: int = 0):
        self.transforms = list(transforms)
        self.seed = seed

    def __call__(self, img: np.ndarray, *, epoch: int = 0, index: int = 0) -> np.ndarray:
        rng = np.random.Generator(np.random.Philox(key=philox_key(self.seed, epoch, index)))
        for t in self.transforms:
            img = t(img, rng)
        return np.ascontiguousarray(img)


def train_transform(height: int, width: int, *, seed: int = 0, p: float = 0.5) -> Compose:
    """The train-phase pipeline of ``dataset/example_dataset.py:35-46``."""
    return Compose(
        [
            resize(height, width),
            random_rotate90(p),
            horizontal_flip(p),
            vertical_flip(p),
            blur(p),
            median_blur(p),
            clahe(p),
            random_brightness_contrast(p),
            random_gamma(p),
            image_compression(p),
            normalize(),
        ],
        seed=seed,
    )


def eval_transform(height: int, width: int) -> Compose:
    """The val-phase pipeline of ``dataset/example_dataset.py:48-50``."""
    return Compose([resize(height, width), normalize()])
