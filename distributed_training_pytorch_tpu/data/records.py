"""Sharded record files — the at-scale input path (BASELINE configs 3-5).

The reference's dataset scans loose image files under ``<root>/<label>/``
(``dataset/example_dataset.py:24-30``) — fine for thousands of files, hopeless
for ImageNet-scale corpora (1.2M+ tiny files: metadata-bound listing, no
sequential I/O). The standard fix on TPU pods is packed record shards
(TFRecord-style); this module implements a dependency-free equivalent:

Layout of one shard (little-endian)::

    magic  b"DTPR1\\0"            6 bytes
    count  u64                     number of records
    count * { label i64, length u64, payload bytes }   back to back
    index  count * u64             byte offset of each record
    index_offset u64               (last 8 bytes) where the index starts

Shards are named ``<prefix>-%05d-of-%05d.rec``. Readers mmap-free: they read
the footer index once (O(count) u64s, not the payloads) and then serve random
access by offset — so a ``ShardedLoader`` permutation touches only the bytes
it needs. Writing is append-only and single-pass.

``RecordFileSource`` plugs into ``ShardedLoader`` exactly like the folder
sources (``__len__``/``__getitem__`` + optional ``transform``).
"""

from __future__ import annotations

import glob
import os
import struct
import threading
from typing import Callable, Iterable, Sequence

import numpy as np

MAGIC = b"DTPR1\x00"


class CorruptRecordError(ValueError):
    """A record is structurally damaged (offset/length outside the shard's
    payload region) or its payload fails to decode. ``ValueError`` subclass
    so pre-existing callers that caught decode ``ValueError``\\ s still do."""


# One probe bound shared by every tolerant layer: past this many consecutive
# corrupt records the corpus (not a record) is broken and we fail loudly.
TOLERANT_PROBE_LIMIT = 9

# Skip counters are bumped from loader worker threads; a shared module lock
# keeps the read-modify-write atomic (contention is one corrupt record's
# worth — negligible) without making sources unpicklable.
_SKIP_COUNT_LOCK = threading.Lock()


def tolerant_fetch(fetch, index: int, n: int, *, exceptions=None):
    """Deterministic skip-and-substitute: try ``fetch((index + k) % n)`` for
    ``k = 0, 1, ...`` until one succeeds; return ``(value, k)`` where ``k``
    is the number of corrupt records skipped (the caller's counter delta).
    Raises :class:`CorruptRecordError` after :data:`TOLERANT_PROBE_LIMIT`
    consecutive failures."""
    exceptions = exceptions or (CorruptRecordError,)
    limit = min(TOLERANT_PROBE_LIMIT, n)
    last_err: Exception | None = None
    for k in range(limit):
        try:
            return fetch((int(index) + k) % n), k
        except exceptions as e:
            last_err = e
    raise CorruptRecordError(
        f"{limit} consecutive corrupt records starting at {int(index)}"
    ) from last_err


class RecordFileWriter:
    """Single-pass writer for one shard. Use :func:`write_shards` for the
    sharded layout."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "wb")  # jaxlint: disable=file-write-without-rank-gate -- dataset-authoring writer: runs offline (shard prep), single-process by contract, never inside a multi-host training job
        self._f.write(MAGIC)
        self._f.write(struct.pack("<Q", 0))  # count, patched on close
        self._offsets: list[int] = []
        self._closed = False

    def append(self, payload: bytes, label: int) -> None:
        self._offsets.append(self._f.tell())
        self._f.write(struct.pack("<qQ", int(label), len(payload)))
        self._f.write(payload)

    def close(self) -> None:
        if self._closed:
            return
        index_offset = self._f.tell()
        for off in self._offsets:
            self._f.write(struct.pack("<Q", off))
        self._f.write(struct.pack("<Q", index_offset))
        self._f.seek(len(MAGIC))
        self._f.write(struct.pack("<Q", len(self._offsets)))
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_shards(
    prefix: str,
    records: Iterable[tuple[bytes, int]],
    *,
    num_shards: int,
) -> list[str]:
    """Round-robin ``(payload, label)`` records into ``num_shards`` shard files
    named ``<prefix>-%05d-of-%05d.rec``; returns the paths."""
    paths = [f"{prefix}-{i:05d}-of-{num_shards:05d}.rec" for i in range(num_shards)]
    writers = [RecordFileWriter(p) for p in paths]
    try:
        for i, (payload, label) in enumerate(records):
            writers[i % num_shards].append(payload, label)
    finally:
        for w in writers:
            w.close()
    return paths


class RecordFileSource:
    """Random-access source over a set of record shards.

    ``pattern`` is a glob (``.../train-*.rec``) or a directory (every ``*.rec``
    inside). ``decode`` maps a payload to the record's ``image`` value —
    default decodes JPEG/PNG bytes to RGB uint8 HWC via cv2/PIL (the native
    csrc runtime decodes from file paths, not memory; in-memory decode stays
    in Python).
    """

    def __init__(
        self,
        pattern: str,
        *,
        decode: Callable[[bytes], np.ndarray] | None = None,
        transform=None,
        skip_corrupt: bool = False,
    ):
        if os.path.isdir(pattern):
            pattern = os.path.join(pattern, "*.rec")
        self.paths = sorted(glob.glob(pattern))
        if not self.paths:
            raise FileNotFoundError(f"no record shards match {pattern}")
        self.decode = decode if decode is not None else decode_image_bytes
        self.transform = transform
        # Graceful degradation (production corpora always contain a few bad
        # records): when on, a structurally-corrupt record is replaced by the
        # next readable one (deterministic — same substitute every epoch/run)
        # and counted in ``corrupt_skipped`` instead of failing the batch.
        self.skip_corrupt = bool(skip_corrupt)
        self.corrupt_skipped = 0
        # Per-shard footer indexes; records ordered shard-major.
        self._shard_offsets: list[np.ndarray] = []
        self._shard_base: list[int] = []
        self._shard_payload_end: list[int] = []  # index_offset: payload region bound
        total = 0
        for path in self.paths:
            with open(path, "rb") as f:
                header = f.read(len(MAGIC) + 8)
                if header[: len(MAGIC)] != MAGIC:
                    raise ValueError(f"{path}: bad magic (not a DTPR1 record file)")
                (count,) = struct.unpack("<Q", header[len(MAGIC) :])
                f.seek(-8, os.SEEK_END)
                (index_offset,) = struct.unpack("<Q", f.read(8))
                f.seek(index_offset)
                offsets = np.frombuffer(f.read(8 * count), dtype="<u8")
            self._shard_offsets.append(offsets)
            self._shard_base.append(total)
            self._shard_payload_end.append(index_offset)
            total += count
        self._len = total
        self._fds: dict[int, int] = {}  # lazy per-shard fds (os.pread access)

    def __len__(self) -> int:
        return self._len

    def _locate(self, index: int) -> tuple[int, int]:
        shard = int(np.searchsorted(self._shard_base, index, side="right")) - 1
        return shard, index - self._shard_base[shard]

    def _fd(self, shard: int) -> int:
        fd = self._fds.get(shard)
        if fd is None:
            fd = os.open(self.paths[shard], os.O_RDONLY)
            winner = self._fds.setdefault(shard, fd)
            if winner != fd:  # lost a racing open; keep the winner's fd
                os.close(fd)
                fd = winner
        return fd

    @staticmethod
    def _native_decodable(payload: bytes) -> bool:
        # the csrc decoders handle JPEG and PNG; anything else (bmp/webp from
        # a packed image folder) falls back to the Python path per record
        return payload[:2] == b"\xff\xd8" or payload[:8] == b"\x89PNG\r\n\x1a\n"

    def _native_positions(self, payloads) -> list:
        """Batch positions the native decoders can take (empty when the
        native lib is off) — the mixed_native_batch split, one place."""
        if getattr(self, "_native", None) is None:
            return []
        return [p for p, pl in enumerate(payloads) if self._native_decodable(pl)]

    def read_record(self, index: int) -> tuple[bytes, int]:
        # os.pread: positioned reads are atomic per call, so loader worker
        # THREADS can share one fd per shard — a seek()+read() pair on a
        # shared handle interleaves across threads and corrupts records.
        shard, local = self._locate(index)
        fd = self._fd(shard)
        offset = int(self._shard_offsets[shard][local])
        payload_end = self._shard_payload_end[shard]
        if offset + 16 > payload_end:
            raise CorruptRecordError(
                f"{self.describe(index)}: header at {offset} beyond payload "
                f"region ({payload_end}) — corrupt index or truncated shard"
            )
        try:
            label, length = struct.unpack("<qQ", os.pread(fd, 16, offset))
        except struct.error as e:  # short pread: shard truncated under us
            raise CorruptRecordError(f"{self.describe(index)}: truncated header") from e
        if offset + 16 + length > payload_end:
            raise CorruptRecordError(
                f"{self.describe(index)}: payload of {length} bytes at {offset} "
                f"overruns the payload region ({payload_end}) — truncated shard"
            )
        payload = os.pread(fd, length, offset + 16)
        if len(payload) != length:
            raise CorruptRecordError(
                f"{self.describe(index)}: short read ({len(payload)}/{length} bytes)"
            )
        return payload, int(label)

    def read_record_tolerant(self, index: int) -> tuple[bytes, int]:
        """``read_record`` honoring ``skip_corrupt``: a corrupt record is
        deterministically replaced by the next readable one (bounded probe)
        and counted in ``corrupt_skipped``."""
        if not self.skip_corrupt:
            return self.read_record(index)
        rec, skipped = tolerant_fetch(self.read_record, index, len(self))
        if skipped:
            with _SKIP_COUNT_LOCK:
                self.corrupt_skipped += skipped
        return rec

    def _produce_batch_tolerant(self, rows, payloads: list, labels: list, produce):
        """Run ``produce(payloads) -> images`` with whole-batch decode
        tolerance: under ``skip_corrupt`` a position whose payload fails to
        decode (structurally fine, bit-rotted content) is substituted by the
        next readable neighbor's (payload, label) pair and the produce is
        retried — the fast path degrades exactly like the per-record path.
        Without ``skip_corrupt``, re-raises the located error."""
        from distributed_training_pytorch_tpu.data.native import DecodeError

        n = len(self)
        shifts: dict[int, int] = {}
        for _ in range(TOLERANT_PROBE_LIMIT + 1):
            try:
                return produce(payloads)
            except DecodeError as e:
                if not self.skip_corrupt:
                    self._raise_located(e, rows)
                p = e.index
                s = shifts.get(p, 0)
                while True:
                    s += 1
                    if s > TOLERANT_PROBE_LIMIT:
                        self._raise_located(e, rows)
                    try:
                        payloads[p], labels[p] = self.read_record(
                            (int(rows[p]) + s) % n
                        )
                        break
                    except CorruptRecordError:
                        continue
                shifts[p] = s
                with _SKIP_COUNT_LOCK:
                    self.corrupt_skipped += 1
        self._raise_located(e, rows)

    def __getitem__(self, index: int) -> dict:
        payload, label = self.read_record_tolerant(int(index))
        try:
            image = self.decode(payload)
        except CorruptRecordError:
            raise
        except ValueError as e:
            raise CorruptRecordError(
                f"failed to decode {self.describe(int(index))}"
            ) from e
        return {"image": image, "label": np.int32(label)}

    def describe(self, index: int) -> str:
        """Human-locatable name for record ``index`` — shard path + position
        inside it (decode-error messages; a batch position alone is useless
        after the epoch shuffle)."""
        shard, local = self._locate(int(index))
        return f"record {int(index)} ({self.paths[shard]} #{local})"

    def _raise_located(self, e, rows):
        """Re-raise a batch-position DecodeError naming the actual record."""
        raise CorruptRecordError(
            f"failed to decode {self.describe(int(rows[e.index]))}"
        ) from None

    def __getstate__(self):
        # fds are not picklable; worker processes reopen lazily.
        state = dict(self.__dict__)
        state["_fds"] = {}
        return state

    def __del__(self, _close=os.close):
        # default arg captures os.close — at interpreter shutdown the module's
        # globals may already be cleared when GC runs this finalizer
        for fd in self.__dict__.get("_fds", {}).values():
            try:
                _close(fd)
            except Exception:
                pass


class NativeRecordFileSource(RecordFileSource):
    """Record source whose batches decode+resize+normalize in one call into
    the native C++ runtime (``data/native.py`` in-memory decoders) — the
    no-augmentation (val/eval) hot path for record shards, mirror of
    ``dataset.NativeImageFolderSource``. Python per-record fallback when the
    native library is unavailable."""

    def __init__(self, pattern: str, height: int, width: int, mean=None, std=None):
        from distributed_training_pytorch_tpu.data import native, transforms

        super().__init__(pattern, transform=None)
        self.height, self.width = height, width
        self.mean = transforms.IMAGENET_MEAN if mean is None else np.asarray(mean, np.float32)
        self.std = transforms.IMAGENET_STD if std is None else np.asarray(std, np.float32)
        self._native = native if native.available() else None
        self._py_transform = transforms.Compose(
            [transforms.resize(height, width), transforms.normalize(self.mean, self.std)]
        )
        if self._native is None:
            self.transform = self._py_transform

    def load_batch(self, rows: np.ndarray, epoch: int) -> dict:
        from distributed_training_pytorch_tpu.data.native import mixed_native_batch

        payloads, labels = map(
            list, zip(*(self.read_record_tolerant(int(i)) for i in rows), strict=True)
        )
        if self._native is not None:

            def produce(pls):
                return mixed_native_batch(
                    len(rows),
                    self.height,
                    self.width,
                    self._native_positions(pls),
                    lambda pos: self._native.decode_resize_normalize_bytes(
                        [pls[p] for p in pos], self.height, self.width, self.mean, self.std
                    ),
                    lambda p: self._py_transform(self.decode(pls[p])),
                )

            images = self._produce_batch_tolerant(rows, payloads, labels, produce)
        else:
            images = np.stack(
                [self._py_transform(self.decode(p)) for p in payloads]
            )
        return {"image": images, "label": np.asarray(labels, np.int32)}


class NativeRecordTrainSource(RecordFileSource):
    """TRAIN-path record source — the full production input pipeline:
    record payload -> native decode+resize (uint8) -> native deterministic
    crop/flip augmentation (uint8) -> ship uint8 to device, where
    ``models.InputNormalizer`` normalizes inside the jitted step (fused into
    the first conv by XLA; the H2D link carries 1 byte/px instead of 4).

    Capability analog of the reference's train pipeline
    (``dataset/example_dataset.py:35-60``: cv2 decode + albumentations
    augment under DataLoader workers), redesigned for the TPU host: one
    GIL-free C++ call per batch for decode and one for augment, Philox-keyed
    per (seed, epoch, record index) so augmentation is deterministic across
    hosts and resumes. Python fallback (same key layout, independent Philox
    draws — each path deterministic, not bit-identical) when the native
    library is unavailable.

    Two augmentation modes (``aug=``):

    * ``"pad_crop"`` — CIFAR-style reflect-pad random crop (+ flip) on the
      resized image; decode and augment are two native batch calls.
    * ``"rrc"`` — ImageNet-style RANDOM-RESIZED-CROP (+ flip), 10-attempt
      sampling with ``transforms.random_resized_crop`` center-square
      fallback, FUSED with the decode in one native call
      (``dtp_decode_rrc_flip_u8_bytes``) so the full-size decode never
      crosses back into Python.

    ``hflip=False`` for orientation-sensitive corpora (digits/text);
    ``train=False`` skips augmentation (uint8 val/eval ship)."""

    def __init__(
        self,
        pattern: str,
        height: int,
        width: int,
        *,
        aug: str = "pad_crop",
        pad: int = 4,
        seed: int = 0,
        hflip: bool = True,
        train: bool = True,
    ):
        from distributed_training_pytorch_tpu.data import native

        if aug not in ("pad_crop", "rrc"):
            raise ValueError(f"aug must be pad_crop|rrc, got {aug!r}")
        super().__init__(pattern, transform=None)
        self.height, self.width = height, width
        self.aug = aug
        self.pad = pad
        self.seed = seed
        self.hflip = hflip
        self.train = train
        self._native = native if native.available() else None

    def _decode_u8(self, payloads) -> np.ndarray:
        """Mixed native/Python decode to a uint8 [N, H, W, 3] batch."""
        from distributed_training_pytorch_tpu.data.native import mixed_native_batch

        def py_row(p: int) -> np.ndarray:
            import cv2

            img = self.decode(payloads[p])
            # cv2 resize keeps uint8; ascontiguousarray for the BGR->RGB view
            return cv2.resize(
                np.ascontiguousarray(img), (self.width, self.height),
                interpolation=cv2.INTER_LINEAR,
            )

        return mixed_native_batch(
            len(payloads),
            self.height,
            self.width,
            self._native_positions(payloads),
            lambda pos: self._native.decode_resize_u8_bytes(
                [payloads[p] for p in pos], self.height, self.width
            ),
            py_row,
            dtype=np.uint8,
        )

    def _augment_py(self, images: np.ndarray, rows: np.ndarray, epoch: int) -> np.ndarray:
        """Numpy fallback: reflect-pad crop + optional hflip, uint8 -> uint8,
        keyed like data/transforms.philox_key."""
        from distributed_training_pytorch_tpu.data.transforms import philox_key

        out = np.empty_like(images)
        h, w = self.height, self.width
        for i, idx in enumerate(rows):
            rng = np.random.Generator(
                np.random.Philox(key=philox_key(self.seed, epoch, int(idx)))
            )
            img = images[i]
            if self.pad:
                padded = np.pad(
                    img, ((self.pad, self.pad), (self.pad, self.pad), (0, 0)),
                    mode="reflect",
                )
                dy, dx = rng.integers(0, 2 * self.pad + 1, size=2)
                img = padded[dy : dy + h, dx : dx + w]
            if self.hflip and rng.random() < 0.5:
                img = img[:, ::-1]
            out[i] = img
        return out

    def _rrc_py(self, payload: bytes, epoch: int, index: int) -> np.ndarray:
        """Per-record Python RRC fallback: decode + transforms.random_resized_crop
        (+ flip), keyed like the native path (independent Philox draws)."""
        from distributed_training_pytorch_tpu.data import transforms as T

        rng = np.random.Generator(
            np.random.Philox(key=T.philox_key(self.seed, epoch, int(index)))
        )
        img = T.random_resized_crop(self.height, self.width)(self.decode(payload), rng)
        if self.hflip and rng.random() < 0.5:
            img = img[:, ::-1]
        return np.ascontiguousarray(img)

    def _load_batch_rrc(self, payloads, rows, epoch: int) -> np.ndarray:
        from distributed_training_pytorch_tpu.data.native import (
            decode_rrc_flip_u8_bytes,
            mixed_native_batch,
        )

        idx = np.asarray(rows, np.int64)
        return mixed_native_batch(
            len(payloads),
            self.height,
            self.width,
            self._native_positions(payloads),
            lambda pos: decode_rrc_flip_u8_bytes(
                [payloads[p] for p in pos], self.height, self.width, idx[pos],
                seed=self.seed, epoch=epoch, hflip=self.hflip,
            ),
            lambda p: self._rrc_py(payloads[p], epoch, int(idx[p])),
            dtype=np.uint8,
        )

    def load_batch(self, rows: np.ndarray, epoch: int) -> dict:
        payloads, labels = map(
            list, zip(*(self.read_record_tolerant(int(i)) for i in rows), strict=True)
        )
        if self.train and self.aug == "rrc":
            images = self._produce_batch_tolerant(
                rows, payloads, labels,
                lambda pls: self._load_batch_rrc(pls, rows, epoch),
            )
            return {"image": images, "label": np.asarray(labels, np.int32)}
        images = self._produce_batch_tolerant(
            rows, payloads, labels, self._decode_u8
        )
        if self.train:
            idx = np.asarray(rows, np.int64)
            if self._native is not None:
                from distributed_training_pytorch_tpu.data.native import augment_crop_flip_u8

                images = augment_crop_flip_u8(
                    images, idx, pad=self.pad, seed=self.seed, epoch=epoch,
                    hflip=self.hflip,
                )
            else:
                images = self._augment_py(images, idx, epoch)
        return {"image": images, "label": np.asarray(labels, np.int32)}


def decode_image_bytes(payload: bytes) -> np.ndarray:
    """JPEG/PNG bytes -> RGB uint8 HWC (cv2 with PIL fallback), matching the
    folder source's ``_decode_image`` contract."""
    try:
        import cv2

        img = cv2.imdecode(np.frombuffer(payload, np.uint8), cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError("cv2 failed to decode record payload")
        return img[:, :, ::-1]  # BGR -> RGB
    except ImportError:
        import io

        from PIL import Image

        return np.asarray(Image.open(io.BytesIO(payload)).convert("RGB"))


def pack_image_folder(
    data_path: str,
    labels: Sequence[str],
    out_prefix: str,
    *,
    num_shards: int = 64,
) -> list[str]:
    """Pack a reference-style ``<root>/<label>/`` tree into record shards (the
    one-time conversion an ImageNet-scale corpus needs before training)."""
    from distributed_training_pytorch_tpu.data.dataset import ImageFolderDataSource

    folder = ImageFolderDataSource(data_path, labels)

    def records():
        for path, label in folder.records:
            with open(path, "rb") as f:
                yield f.read(), label

    return write_shards(out_prefix, records(), num_shards=num_shards)
